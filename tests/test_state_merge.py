"""State-merge plugin tests (capability parity:
reference tests/integration_tests/state_merge_tests.py + the
check_mergeability/merge_states unit behavior)."""

import pytest

from mythril_tpu.core.plugin.plugins.state_merge import (
    check_ws_merge_condition, merge_states, MergeAnnotation)
from mythril_tpu.core.state.world_state import WorldState
from mythril_tpu.smt import symbol_factory
from mythril_tpu.smt.solver import sat
from mythril_tpu.support.support_args import args

pytestmark = pytest.mark.skipif(not sat.have_native(),
                                reason="native CDCL build required")

ADDRESS = 0x0ACE0001


def _ws_pair():
    # earlier tests may leave keccak axioms on the process-wide manager;
    # this test's constraint sets must be self-contained
    from mythril_tpu.core.function_managers import keccak_function_manager
    from mythril_tpu.smt.solver.solver import reset_solver_backend

    keccak_function_manager.reset()
    # a pool fattened by earlier heavy tests (solver corpus) makes each
    # is_possible() slow enough to time out — and timeouts count as
    # possible, flipping this test's unsat assertions
    reset_solver_backend()
    selector = symbol_factory.BitVecSym("merge_sel", 256)
    ws_a = WorldState()
    ws_a.create_account(balance=0, address=ADDRESS)
    slot = symbol_factory.BitVecVal(0, 256)
    ws_a.constraints.append(selector == 1)
    ws_a.accounts[ADDRESS].storage[slot] = 11

    ws_b = WorldState()
    ws_b.create_account(balance=0, address=ADDRESS)
    ws_b.constraints.append(selector == 2)
    ws_b.accounts[ADDRESS].storage[slot] = 22
    return selector, ws_a, ws_b


def test_mergeable_pair_detected():
    _, ws_a, ws_b = _ws_pair()
    assert check_ws_merge_condition(ws_a, ws_b)


def test_merge_preserves_per_branch_storage():
    selector, ws_a, ws_b = _ws_pair()
    merge_states(ws_a, ws_b)
    assert list(ws_a.get_annotations(MergeAnnotation))

    storage_value = ws_a.accounts[ADDRESS].storage[
        symbol_factory.BitVecVal(0, 256)]
    base = list(ws_a.constraints)
    from mythril_tpu.core.state.constraints import Constraints

    # under selector==1 the merged storage must still read 11, never 22
    assert Constraints(base + [selector == 1, storage_value == 11]).is_possible()
    assert not Constraints(base + [selector == 1, storage_value == 22]).is_possible()
    # and symmetrically for the other branch
    assert Constraints(base + [selector == 2, storage_value == 22]).is_possible()
    assert not Constraints(base + [selector == 2, storage_value == 11]).is_possible()
    # both branches remain reachable
    assert Constraints(base + [selector == 1]).is_possible()
    assert Constraints(base + [selector == 2]).is_possible()
    # but no third path appeared
    assert not Constraints(base + [selector == 3]).is_possible()


def test_unmergeable_when_too_different():
    selector, ws_a, ws_b = _ws_pair()
    for i in range(20):
        ws_b.constraints.append(
            symbol_factory.BitVecSym(f"merge_extra{i}", 256) == i)
    assert not check_ws_merge_condition(ws_a, ws_b)


def test_e2e_findings_unchanged_with_merging():
    """--enable-state-merging must not change the issue set."""
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from test_analysis import analyze, KILLBILLY

    baseline = analyze(KILLBILLY, modules=["AccidentallyKillable"], tx_count=2)
    args.enable_state_merging = True
    try:
        merged = analyze(KILLBILLY, modules=["AccidentallyKillable"], tx_count=2)
    finally:
        args.enable_state_merging = False
    assert sorted(i.swc_id for i in merged) == sorted(
        i.swc_id for i in baseline) == ["106"]
