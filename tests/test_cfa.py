"""Static control-flow analysis tests (mythril_tpu/staticanalysis/).

Host/AST-only except the one A/B parity case (a mini-killbilly symbolic
run with the screen on vs off): synthetic bytecode CFGs, the post-
dominator tree against a brute-force set-intersection reference on
random small graphs, table-shape invariants, the cfa_screen consumer
surface, the cfaview CLI, and a corpus smoke (vendored headline
contracts when the reference corpus is not mounted)."""

import json
import os
import random
import subprocess
import sys

import pytest

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from mythril_tpu.frontends.asm import assemble, dispatcher
from mythril_tpu.frontends.disassembler import Disassembly
from mythril_tpu.observe import metrics
from mythril_tpu.smt.solver import cfa_screen
from mythril_tpu.staticanalysis import (build_cfa, compute_idoms, get_cfa,
                                        postorder)
from mythril_tpu.support.support_args import args

REFERENCE_CORPUS = "/root/reference/solidity_examples"


@pytest.fixture(autouse=True)
def _clean_slate():
    metrics.reset()
    saved = getattr(args, "cfa", True)
    yield
    args.cfa = saved
    metrics.reset()


def _cfa(source: str):
    result = build_cfa(Disassembly(assemble(source).hex()))
    assert result is not None
    return result


# -- synthetic bytecode cases --------------------------------------------------------


DIAMOND = """
PUSH1 0x00
CALLDATALOAD
PUSH @then
JUMPI
PUSH1 0x01
PUSH @end
JUMP
then:
JUMPDEST
PUSH1 0x02
end:
JUMPDEST
POP
STOP
"""


def test_diamond_merge_point():
    result = _cfa(DIAMOND)
    assert result.fully_resolved
    # both arms reconverge at the end: JUMPDEST — exactly one merge point
    [merge_pc] = result.merge_points
    assert result.valid_target_bitmap[merge_pc] == 1
    # the branch site maps to it, and both arms' blocks report it
    assert set(result.branch_merge_pc.values()) == {merge_pc}
    for site, targets in result.jump_targets.items():
        assert all(t in result.valid_targets for t in targets)


def test_loop_resolves_backedge():
    result = _cfa("""
PUSH1 0x05
head:
JUMPDEST
PUSH1 0x01
SWAP1
SUB
DUP1
PUSH @head
JUMPI
POP
STOP
""")
    assert result.fully_resolved
    # the JUMPI's taken edge is the backedge to head:
    [(site, targets)] = list(result.jump_targets.items())
    assert len(targets) == 1
    assert targets[0] < site  # jumps backwards
    assert targets[0] in result.valid_targets


def test_dead_code_past_unconditional_jump():
    result = _cfa("""
PUSH @end
JUMP
PUSH1 0xFF
PUSH1 0xEE
POP
POP
end:
JUMPDEST
STOP
""")
    assert result.fully_resolved
    [(_, (target,))] = list(result.jump_targets.items())
    # everything between the JUMP and the landing JUMPDEST is dead
    jump_end = 4  # PUSH2 (3 bytes) + JUMP
    assert all(result.dead_mask[pc] for pc in range(jump_end, target))
    assert result.dead_bytes == target - jump_end
    assert not result.is_dead(target)
    assert not any(result.dead_mask[:jump_end])


def test_unresolvable_dynamic_jump_fans_out():
    result = _cfa("""
PUSH1 0x00
CALLDATALOAD
JUMP
a:
JUMPDEST
STOP
b:
JUMPDEST
STOP
""")
    assert not result.fully_resolved
    [site] = result.unresolved_jumps
    assert result.resolved_targets(site) is None
    # conservative fan-out: every JUMPDEST stays reachable + valid
    assert len(result.valid_targets) == 2
    assert result.dead_bytes == 0


def test_constant_flows_through_dup_swap_and_mask():
    # target survives DUP/SWAP shuffling and an AND mask (solc idiom)
    result = _cfa("""
PUSH2 0x0FFF
PUSH @end
AND
PUSH1 0x2a
SWAP1
JUMP
end:
JUMPDEST
POP
STOP
""")
    assert result.fully_resolved
    [(_, targets)] = list(result.jump_targets.items())
    assert len(targets) == 1
    assert targets[0] in result.valid_targets


def test_constant_invalid_target_is_provable_throw():
    # jumps into the middle of a PUSH immediate: no JUMPDEST there
    result = _cfa("PUSH1 0x01\nJUMP\nJUMPDEST\nSTOP")
    [(site, targets)] = list(result.jump_targets.items())
    assert targets == ()  # provably throws


def test_pc_opcode_is_a_known_constant():
    result = _cfa("""
PC
PUSH1 0x03
ADD
JUMP
JUMPDEST
STOP
""")
    # PC pushes 0; 0 + 4... the JUMPDEST sits right after JUMP at pc 4
    assert result.fully_resolved


def test_bail_over_block_budget():
    source = "\n".join(["JUMPDEST"] * 40) + "\nSTOP"
    dis = Disassembly(assemble(source).hex())
    assert build_cfa(dis, max_blocks=8) is None
    assert build_cfa(dis) is not None


# -- dense-table invariants ----------------------------------------------------------


def test_table_shapes_and_memoization():
    dis = Disassembly(assemble(DIAMOND).hex())
    result = get_cfa(dis)
    assert result is get_cfa(dis)  # memoized on the instance
    n = result.code_length
    assert len(result.pc_to_block) == n
    assert len(result.valid_target_bitmap) == n
    assert len(result.dead_mask) == n
    assert len(result.block_merge_pc) == len(result.blocks)
    assert result.exit_id == len(result.blocks)
    # every byte of a block maps back to it; immediates inherit the block
    for block in result.blocks:
        for pc in range(block.start_pc, block.end_pc):
            assert result.pc_to_block[pc] == block.block_id
    # bitmap agrees with the set form
    assert {pc for pc, bit in enumerate(result.valid_target_bitmap)
            if bit} == result.valid_targets
    # refined bitmap is a subset of the disassembler's unrefined one
    assert result.valid_targets <= dis.valid_jump_destinations


def test_metrics_emitted_on_build():
    get_cfa(Disassembly(assemble(DIAMOND).hex()))
    snapshot = metrics.snapshot()
    assert snapshot["cfa.blocks"] > 0
    assert snapshot["cfa.jumps_resolved"] == 2
    assert snapshot["cfa.merge_points"] == 1


# -- post-dominators vs a brute-force reference --------------------------------------


def _dom_sets(succs, entry):
    """Reference: iterative full dominator *sets* to a fixed point, over
    the reachable subgraph only (unreachable preds contribute nothing)."""
    reachable = set(postorder(succs, entry))
    preds = {node: [] for node in reachable}
    for node in reachable:
        for nxt in succs[node]:
            if nxt in reachable:
                preds[nxt].append(node)
    dom = {node: set(reachable) for node in reachable}
    dom[entry] = {entry}
    changed = True
    while changed:
        changed = False
        for node in reachable:
            if node == entry:
                continue
            new = set(reachable)
            for pred in preds[node]:
                new &= dom[pred]
            new |= {node}
            if new != dom[node]:
                dom[node] = new
                changed = True
    return dom, reachable


def _idom_from_sets(dom, reachable, entry, n):
    """Unique strict dominator dominated by all other strict dominators."""
    idom = [None] * n
    idom[entry] = entry
    for node in reachable:
        if node == entry:
            continue
        strict = (dom[node] - {node}) & reachable
        for cand in strict:
            # the immediate dominator is the LOWEST strict dominator:
            # every other strict dominator of `node` dominates it
            if all(other in dom[cand] for other in strict):
                idom[node] = cand
                break
    return idom


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_idoms_match_brute_force_on_random_graphs(seed):
    rng = random.Random(seed)
    n = rng.randint(4, 12)
    succs = [[] for _ in range(n)]
    for node in range(n):
        for _ in range(rng.randint(0, 3)):
            succs[node].append(rng.randrange(n))
    fast = compute_idoms(succs, entry=0)
    dom, reachable = _dom_sets(succs, entry=0)
    ref = _idom_from_sets(dom, reachable, entry=0, n=n)
    for node in range(n):
        if node in reachable:
            assert fast[node] == ref[node], (seed, node, succs)
        else:
            assert fast[node] is None


def test_postdom_is_idom_on_reversed_diamond():
    #   0 -> 1 -> 3, 0 -> 2 -> 3, 3 -> 4(exit)
    succs = [[1, 2], [3], [3], [4], []]
    reverse = [[] for _ in succs]
    for node, nexts in enumerate(succs):
        for nxt in nexts:
            reverse[nxt].append(node)
    ipostdom = compute_idoms(reverse, entry=4)
    assert ipostdom[0] == 3  # the branch post-dominates at the join
    assert ipostdom[1] == 3 and ipostdom[2] == 3
    assert ipostdom[3] == 4


# -- the cfa_screen consumer surface -------------------------------------------------


def test_screen_verdicts_and_counters():
    dis = Disassembly(assemble(DIAMOND).hex())
    result = get_cfa(dis)
    [merge_pc] = result.merge_points
    assert cfa_screen.screen_jump_target(dis, merge_pc) is True
    assert cfa_screen.screen_jump_target(dis, 0) is False  # not a JUMPDEST
    assert cfa_screen.screen_jump_target(dis, 10_000) is None  # out of range
    snapshot = metrics.snapshot()
    assert snapshot["cfa.screen.answered"] == 2
    assert snapshot["cfa.screen.infeasible"] == 1


def test_screen_agrees_with_dynamic_check_everywhere():
    """Soundness/parity: on a fully-resolved contract the screen verdict
    equals the dynamic index_of_address + JUMPDEST check for EVERY
    in-range address — the A/B-identical-results argument, exhaustively."""
    for source in (DIAMOND, dispatcher({"f()": "JUMPDEST\nSTOP"})):
        dis = Disassembly(assemble(source).hex())
        result = get_cfa(dis)
        assert result.fully_resolved
        for pc in range(result.code_length):
            dynamic = (dis.index_of_address(pc) is not None
                       and dis.instruction_list[
                           dis.index_of_address(pc)].op_code == "JUMPDEST")
            static = cfa_screen.screen_jump_target(dis, pc)
            if dynamic:
                assert static is True, pc
            else:
                assert static in (False, None), pc


def test_no_cfa_flag_disables_every_verdict():
    dis = Disassembly(assemble(DIAMOND).hex())
    args.cfa = False
    assert not cfa_screen.enabled()
    assert cfa_screen.screen_jump_target(dis, 0) is None
    assert cfa_screen.resolved_jump_targets(dis, 0) is None
    assert cfa_screen.merge_point_at(dis, 0) is None
    assert not cfa_screen.statically_dead(dis, 0)
    assert cfa_screen.block_key(dis, 7) == 7  # raw-pc fallback
    assert "cfa.screen.answered" not in metrics.snapshot()


def test_knob_disables_the_pass(monkeypatch):
    monkeypatch.setenv("MYTHRIL_TPU_CFA", "0")
    dis = Disassembly(assemble(DIAMOND).hex())
    assert get_cfa(dis) is None


def test_block_key_maps_into_block_start():
    dis = Disassembly(assemble(DIAMOND).hex())
    result = get_cfa(dis)
    for block in result.blocks:
        if block.block_id in result.reachable:
            assert cfa_screen.block_key(dis, block.start_pc) \
                == block.start_pc


# -- A/B parity: screen on vs off, identical detections ------------------------------


def _analyze_killbilly():
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import creation_wrapper

    contract = {
        "activatekillability()": "PUSH1 0x01\nPUSH1 0x00\nSSTORE\nSTOP",
        "commencekilling()":
            "PUSH1 0x00\nSLOAD\nPUSH1 0x01\nEQ\nPUSH @do_kill\nJUMPI\nSTOP\n"
            "do_kill:\nJUMPDEST\nCALLER\nSELFDESTRUCT",
    }
    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(contract)))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=60, create_timeout=20, transaction_count=2,
        modules=["AccidentallyKillable"], compulsory_statespace=False)
    issues = fire_lasers(wrapper, white_list=["AccidentallyKillable"])
    return sorted((issue.swc_id, issue.address) for issue in issues)


def test_ab_parity_and_answered_counter():
    args.cfa = True
    with_cfa = _analyze_killbilly()
    answered = metrics.snapshot().get("cfa.screen.answered", 0)
    assert answered > 0  # the screen decided real jump queries
    metrics.reset()
    args.cfa = False
    without_cfa = _analyze_killbilly()
    assert metrics.snapshot().get("cfa.screen.answered", 0) == 0
    assert with_cfa == without_cfa  # identical detections
    assert with_cfa  # and the SWC-106 was actually found
    assert with_cfa[0][0] == "106"


@pytest.mark.slow
def test_ab_parity_full_killbilly():
    """The headline 3-tx killbilly (vendored), screen on vs off."""
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import creation_wrapper
    from tools.measure_headline import KILLBILLY

    def run():
        reset_callback_modules()
        creation = creation_wrapper(assemble(dispatcher(KILLBILLY)))
        wrapper = SymExecWrapper(
            creation.hex(), address=None, strategy="bfs", max_depth=128,
            execution_timeout=120, create_timeout=20, transaction_count=3,
            modules=["AccidentallyKillable"], compulsory_statespace=False)
        issues = fire_lasers(wrapper, white_list=["AccidentallyKillable"])
        return sorted((issue.swc_id, issue.address) for issue in issues)

    args.cfa = True
    with_cfa = run()
    assert metrics.snapshot().get("cfa.screen.answered", 0) > 0
    metrics.reset()
    args.cfa = False
    without_cfa = run()
    assert with_cfa == without_cfa


# -- corpus smoke --------------------------------------------------------------------


def _corpus_bytecodes():
    """(name, hex) for every corpus contract whose bytecode is on disk;
    vendored headline contracts when the reference tree is absent."""
    out = []
    names = sorted(json.load(
        open(os.path.join(REPO_ROOT, "tests", "data", "corpus",
                          "corpus_host.json")))["contracts"])
    for name in names:
        path = os.path.join(REFERENCE_CORPUS, name)
        if os.path.exists(path):
            with open(path) as handle:
                out.append((name, handle.read().strip()))
    if not out:
        from tools.measure_headline import BECTOKEN, KILLBILLY

        out = [(name, assemble(dispatcher(spec)).hex())
               for name, spec in (("killbilly", KILLBILLY),
                                  ("bectoken", BECTOKEN))]
    return out


def test_corpus_smoke_resolution_rate():
    contracts = _corpus_bytecodes()
    assert contracts
    resolved = 0
    for name, bytecode in contracts:
        result = build_cfa(Disassembly(bytecode))
        assert result is not None, name
        assert result.n_jump_sites > 0, name
        assert len(result.valid_targets) > 0, name
        if result.fully_resolved:
            resolved += 1
    # the acceptance bar: cfa fully resolves >= 80% of the corpus
    assert resolved / len(contracts) >= 0.8, (resolved, len(contracts))


def test_cfaview_reports_corpus_contracts():
    from tools import cfaview

    for name, bytecode in _corpus_bytecodes():
        dis = Disassembly(bytecode)
        result = build_cfa(dis)
        text = cfaview.report(result, dis.instruction_list)
        assert "== merge points" in text, name
        assert "== blocks ==" in text, name


# -- cfaview CLI ---------------------------------------------------------------------


def _cfaview(*argv):
    return subprocess.run(
        [sys.executable, "-m", "tools.cfaview", *argv],
        cwd=REPO_ROOT, capture_output=True, text=True)


def test_cfaview_cli_on_vendored_contract():
    proc = _cfaview("killbilly")
    assert proc.returncode == 0, proc.stderr
    assert "fully resolved" in proc.stdout
    assert "== merge points" in proc.stdout


def test_cfaview_cli_on_hex_string():
    bytecode = assemble(DIAMOND).hex()
    proc = _cfaview(bytecode)
    assert proc.returncode == 0, proc.stderr
    assert "merge points: 1" in proc.stdout


def test_cfaview_cli_rejects_garbage():
    proc = _cfaview("not-hex-not-a-file")
    assert proc.returncode == 2
    assert "cannot load" in proc.stderr


def test_cfaview_cli_taint_sections_on_killbilly():
    """Golden surface of `--taint` on the vendored killbilly: recovered
    selectors, the SELFDESTRUCT sink verdict, and the module screen."""
    proc = _cfaview("killbilly", "--taint")
    assert proc.returncode == 0, proc.stderr
    assert "== taint: functions ==" in proc.stdout
    assert "activatekillability()" in proc.stdout
    assert "commencekilling()" in proc.stdout
    assert "== taint: natural loops ==" in proc.stdout
    assert "SELFDESTRUCT" in proc.stdout
    assert "[0]=caller" in proc.stdout
    assert "== taint: module screen ==" in proc.stdout
    assert "ExternalCalls" in proc.stdout  # no CALL in killbilly


def test_cfaview_cli_taint_json_roundtrips():
    proc = _cfaview("bectoken", "--taint", "--json")
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    taint = doc["taint"]
    assert len(taint["functions"]) == 2
    assert "AccidentallyKillable" in taint["screened_modules"]
    from mythril_tpu.staticanalysis import ContractSummary

    summary = ContractSummary.from_json(taint)
    assert summary is not None
    assert summary.n_sink_sites == len(taint["sink_sites"])
