"""`--engine tpu` parity tests (VERDICT r3 done-criterion: the device
symbolic frontier must find the same issues as the host engine on the test
contracts, with exploration demonstrably on device).

The frontier (parallel/frontier.py) runs the dispatch/require/storage-guard
region of each transaction on device and materializes escaping lanes into
host GlobalStates; these tests assert issue-set equality against host-only
runs plus frontier-level invariants (forks happened, lanes escaped at
detector-relevant sites)."""

import os
import sys

os.environ.setdefault("MYTHRIL_TPU_LANES", "16")  # small batch: CI shapes

import pytest

jax = pytest.importorskip("jax")

sys.path.insert(0, os.path.dirname(__file__))

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontends.asm import (assemble, creation_wrapper, dispatcher,
                                       selector)
from mythril_tpu.smt.solver import sat

pytestmark = pytest.mark.skipif(not sat.have_native(),
                                reason="native CDCL build required")


def analyze_with_engine(runtime_src, modules, tx_count, engine):
    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(runtime_src))
                                if isinstance(runtime_src, dict)
                                else assemble(runtime_src))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30, transaction_count=tx_count,
        modules=modules, compulsory_statespace=False, engine=engine)
    return fire_lasers(wrapper, white_list=modules)


def test_killbilly_parity():
    """2-tx selfdestruct chain: device explores activate/kill dispatch,
    host detector fires at the materialized SELFDESTRUCT."""
    from test_analysis import KILLBILLY

    host = analyze_with_engine(KILLBILLY, ["AccidentallyKillable"], 2, "host")
    tpu = analyze_with_engine(KILLBILLY, ["AccidentallyKillable"], 2, "tpu")
    assert sorted(i.swc_id for i in tpu) == sorted(
        i.swc_id for i in host) == ["106"]
    # witness parity: the kill still requires the activation call first
    steps = tpu[0].transaction_sequence["steps"]
    assert steps[-1]["input"].startswith(
        "0x%08x" % selector("commencekilling()"))


def test_safe_contract_stays_clean():
    from test_analysis import SAFE_KILL

    tpu = analyze_with_engine(SAFE_KILL, ["AccidentallyKillable"], 2, "tpu")
    assert tpu == []


def test_origin_dependence_parity():
    """tx.origin in a branch condition: the frontier must hand the JUMPI to
    the host (origin-tainted conditions are never forked on device) so the
    TxOrigin detector sees it."""
    contract = {
        "auth()": "ORIGIN\nPUSH1 0x42\nEQ\nPUSH @ok\nJUMPI\nSTOP\n"
                  "ok:\nJUMPDEST\nPUSH1 0x01\nPUSH1 0x00\nSSTORE\nSTOP",
    }
    host = analyze_with_engine(contract, ["TxOrigin"], 1, "host")
    tpu = analyze_with_engine(contract, ["TxOrigin"], 1, "tpu")
    assert sorted(i.swc_id for i in tpu) == sorted(
        i.swc_id for i in host) == ["115"]


def test_transaction_sequences_respected_by_tpu_engine():
    """--transaction-sequences / prioritizer selector restrictions must bind
    under `--engine tpu` exactly as on host (VERDICT r3 weak #7: the TPU path
    dropped func_hashes): restricting tx1 to the wrong function must kill the
    2-tx selfdestruct chain; the right sequence must find it."""
    from mythril_tpu.support.support_args import args
    from test_analysis import KILLBILLY

    try:
        args.transaction_sequences = [[selector("activatekillability()")],
                                      [selector("commencekilling()")]]
        found = analyze_with_engine(KILLBILLY, ["AccidentallyKillable"], 2,
                                    "tpu")
        args.transaction_sequences = [[selector("commencekilling()")],
                                      [selector("commencekilling()")]]
        not_found = analyze_with_engine(KILLBILLY, ["AccidentallyKillable"],
                                        2, "tpu")
    finally:
        args.transaction_sequences = None
    assert sorted(i.swc_id for i in found) == ["106"]
    assert not_found == []


def _capture_frontier_log():
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _Capture()
    logger = logging.getLogger("mythril_tpu.parallel.frontier")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    return handler, logger, records


def analyze_runtime_with_engine(runtime_src, modules, tx_count, engine,
                                address=0xDEADBEEF):
    """Deployed-bytecode analysis (the CLI's --bin-runtime / -a path): fresh
    world state, concrete_storage=False — i.e. a SYMBOLIC storage base array,
    the case that forced a host fallback in round 3."""
    import types

    reset_callback_modules()
    runtime = assemble(dispatcher(runtime_src)
                       if isinstance(runtime_src, dict) else runtime_src)
    contract = types.SimpleNamespace(code=runtime.hex(), name="Runtime")
    wrapper = SymExecWrapper(
        contract, address=address, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30, transaction_count=tx_count,
        modules=modules, compulsory_statespace=False, engine=engine)
    return fire_lasers(wrapper, white_list=modules)


def test_runtime_code_engages_device():
    """Symbolic-base storage (every --bin-runtime/-a analysis) must ENGAGE
    the device frontier: cold SLOADs fault in as Select(base, key) host-term
    leaves (frontier._cold_sload_lane) instead of falling back to a pure
    host run, and the issue set must match the host engine's."""
    from test_analysis import KILLBILLY

    host = analyze_runtime_with_engine(KILLBILLY, ["AccidentallyKillable"],
                                       2, "host")
    handler, logger, records = _capture_frontier_log()
    try:
        tpu = analyze_runtime_with_engine(KILLBILLY, ["AccidentallyKillable"],
                                          2, "tpu")
    finally:
        logger.removeHandler(handler)
    assert sorted(i.swc_id for i in tpu) == sorted(
        i.swc_id for i in host) == ["106"]
    assert not any("host fallback" in m or "runs entirely on the host" in m
                   for m in records), f"device never engaged: {records}"
    frontier_lines = [m for m in records if " forks" in m]
    assert frontier_lines, "frontier never ran"
    total_forks = sum(int(m.split("frontier: ")[1].split(" forks")[0])
                      for m in frontier_lines)
    total_faults = sum(int(m.split(" forks, ")[1].split(" storage")[0])
                       for m in frontier_lines)
    assert total_forks > 0, f"no device forks: {frontier_lines}"
    assert total_faults > 0, f"no storage fault-ins: {frontier_lines}"


def test_symbolic_storage_key_stays_on_device():
    """A tx-1 SSTORE with a SYMBOLIC key (`mapping[msg.sender]`-style —
    every token contract) must NOT force tx 2 into a whole-transaction host
    fallback: the chain walk stops at the symbolic-key store and cold
    SLOADs fault in Select(chain, key) (frontier._storage_entries)."""
    contract = {
        # tx1: storage[caller] = 1 (symbolic key), storage[3] = 7 (concrete)
        "setup()": "PUSH1 0x01\nCALLER\nSSTORE\n"
                   "PUSH1 0x07\nPUSH1 0x03\nSSTORE\nSTOP",
        # tx2: a concrete-key read (possibly shadowed by the symbolic store)
        # guards a selfdestruct
        "drain()": "PUSH1 0x03\nSLOAD\nPUSH1 0x07\nEQ\nPUSH @kill\nJUMPI\n"
                   "STOP\nkill:\nJUMPDEST\nCALLER\nSELFDESTRUCT",
    }
    host = analyze_with_engine(contract, ["AccidentallyKillable"], 2, "host")
    handler, logger, records = _capture_frontier_log()
    try:
        tpu = analyze_with_engine(contract, ["AccidentallyKillable"], 2,
                                  "tpu")
    finally:
        logger.removeHandler(handler)
    assert sorted(i.swc_id for i in tpu) == sorted(
        i.swc_id for i in host) == ["106"]
    assert not any("runs entirely on the host" in m for m in records), \
        f"symbolic-key storage forced a host fallback: {records}"
    # both transactions' frontiers ran (one log line per device phase)
    assert len([m for m in records if " forks" in m]) >= 2, records


def test_frontier_forks_on_device():
    """The exploration must demonstrably run on device: symbolic JUMPI forks
    are serviced by the frontier, not the host engine."""
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _Capture()
    logging.getLogger("mythril_tpu.parallel.frontier").addHandler(handler)
    logging.getLogger("mythril_tpu.parallel.frontier").setLevel(logging.INFO)
    try:
        from test_analysis import KILLBILLY

        analyze_with_engine(KILLBILLY, ["AccidentallyKillable"], 2, "tpu")
    finally:
        logging.getLogger("mythril_tpu.parallel.frontier").removeHandler(
            handler)
    frontier_lines = [m for m in records if "forks" in m]
    assert frontier_lines, "frontier never ran"
    total_forks = sum(int(m.split("frontier: ")[1].split(" forks")[0])
                      for m in frontier_lines)
    assert total_forks >= 2, f"too few device forks: {frontier_lines}"
