"""Findings-parity lock over the vendored corpus measurements
(VERDICT r4 next-round #2 done-criterion: per-contract corpus_tpu SWC sets
must be a superset of corpus_host at equal budget).

tools/measure_corpus.py writes corpus_{engine}.json at the repo root from
real equal-budget sweeps (the tpu sweep on the chip, the host sweep on
CPU); the blessed snapshots are vendored under tests/data/corpus/ so this
test locks them while the repo-root outputs stay untracked run artifacts.
The sweeps themselves are too slow for CI (19 contracts x 2 engines x
90 s) — re-run the tool after engine changes and refresh the vendored
jsons.
"""

import json
import os

import pytest

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "corpus")


def _load(engine):
    path = os.path.join(FIXTURES, f"corpus_{engine}.json")
    if not os.path.exists(path):
        pytest.skip(f"{path} not measured")
    with open(path) as handle:
        return json.load(handle)


def test_tpu_swc_sets_cover_host():
    host = _load("host")
    tpu = _load("tpu")
    assert host["budget_s"] == tpu["budget_s"], \
        "corpus sweeps measured at different budgets are not comparable"
    missing = {}
    for name, host_result in host["contracts"].items():
        host_swc = set(host_result.get("swc") or [])
        tpu_swc = set(tpu["contracts"].get(name, {}).get("swc") or [])
        if not host_swc <= tpu_swc:
            missing[name] = sorted(host_swc - tpu_swc)
    assert not missing, \
        f"tpu engine misses host findings at equal budget: {missing}"


def test_tpu_total_findings_at_least_host():
    host = _load("host")
    tpu = _load("tpu")
    assert tpu["total_swc_findings"] >= host["total_swc_findings"] * 0.9, (
        f"tpu total findings collapsed: {tpu['total_swc_findings']} vs "
        f"host {host['total_swc_findings']}")
