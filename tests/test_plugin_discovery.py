"""Extension discovery tests (capability parity: reference
mythril/plugin/discovery.py + loader.py — third-party detector/plugin
packages via entry points)."""

import pytest

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.core.plugin.interface import LaserPlugin
from mythril_tpu.core.plugin.loader import LaserPluginLoader
from mythril_tpu.plugin import (MythrilLaserPlugin, MythrilPlugin,
                                MythrilPluginLoader, PluginDiscovery,
                                UnsupportedPluginType)


class FakeDetector(DetectionModule, MythrilPlugin):
    name = "fake-detector"
    swc_id = "000"
    description = "test detector"
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["STOP"]
    taint_sinks = {"STOP": ()}
    plugin_default_enabled = True

    def _execute(self, state):
        return []


class FakeLaserPlugin(MythrilLaserPlugin):
    name = "fake-laser-plugin"
    plugin_default_enabled = True

    def __call__(self, *args, **kwargs):
        class _Plugin(LaserPlugin):
            def initialize(self, symbolic_vm):
                pass

        return _Plugin()


@pytest.fixture
def discovery(monkeypatch):
    instance = PluginDiscovery()
    monkeypatch.setattr(instance, "_installed_plugins",
                        {"fake-detector": FakeDetector,
                         "fake-laser-plugin": FakeLaserPlugin})
    return instance


def test_discovery_listing(discovery):
    assert discovery.is_installed("fake-detector")
    assert not discovery.is_installed("nope")
    assert set(discovery.get_plugins()) == {"fake-detector",
                                            "fake-laser-plugin"}
    assert set(discovery.get_plugins(default_enabled=True)) == {
        "fake-detector", "fake-laser-plugin"}


def test_build_plugin(discovery):
    plugin = discovery.build_plugin("fake-detector")
    assert isinstance(plugin, FakeDetector)
    with pytest.raises(ValueError):
        discovery.build_plugin("missing")


def test_loader_dispatch(discovery):
    loader = MythrilPluginLoader()
    detector = discovery.build_plugin("fake-detector")
    loader.load(detector)
    registered = [type(m).__name__
                  for m in ModuleLoader().get_detection_modules()]
    assert "FakeDetector" in registered
    # laser plugins land in the engine plugin loader as builders
    laser = discovery.build_plugin("fake-laser-plugin")
    loader.load(laser)
    assert "fake-laser-plugin" in LaserPluginLoader().laser_plugin_builders

    class Unknown(MythrilPlugin):
        pass

    with pytest.raises(UnsupportedPluginType):
        loader.load(Unknown())

    # cleanup: drop the fake detector so later tests see the stock 18
    ModuleLoader()._modules = [
        m for m in ModuleLoader()._modules
        if type(m).__name__ != "FakeDetector"]
    LaserPluginLoader().laser_plugin_builders.pop("fake-laser-plugin", None)
