"""Per-opcode unit tests (test-strategy parity: reference tests/instructions/*):
hand-built GlobalState, call Instruction(op).evaluate directly, assert
stack/memory/exception effects."""

import pytest

from mythril_tpu.core.instructions import Instruction
from mythril_tpu.core.state import (Account, Environment, GlobalState,
                                    MachineState, WorldState)
from mythril_tpu.core.state.calldata import ConcreteCalldata
from mythril_tpu.core.transaction.transaction_models import MessageCallTransaction
from mythril_tpu.core.util import InvalidInstruction, WriteProtection
from mythril_tpu.frontends.disassembler import Disassembly
from mythril_tpu.smt import symbol_factory


def make_state(code_hex: str = "", static: bool = False,
               calldata=None) -> GlobalState:
    world_state = WorldState()
    account = world_state.create_account(balance=10 ** 18, address=0x1AAF)
    account.code = Disassembly(code_hex or "0x60")
    environment = Environment(
        active_account=account,
        sender=symbol_factory.BitVecVal(0xCAFE, 256),
        calldata=calldata or ConcreteCalldata("1", []),
        gasprice=symbol_factory.BitVecVal(1, 256),
        callvalue=symbol_factory.BitVecVal(0, 256),
        origin=symbol_factory.BitVecVal(0xCAFE, 256),
        basefee=symbol_factory.BitVecVal(7, 256),
        static=static,
    )
    state = GlobalState(world_state, environment, None,
                        MachineState(gas_limit=8000000))
    transaction = MessageCallTransaction(
        world_state=world_state, callee_account=account,
        caller=environment.sender, identifier="1", gas_limit=8000000)
    state.transaction_stack.append((transaction, None))
    return state


def push(state, *values):
    for value in values:
        state.mstate.stack.append(symbol_factory.BitVecVal(value, 256))


def run(state, op):
    return Instruction(op).evaluate(state)


M = 2 ** 256


@pytest.mark.parametrize("op,inputs,expected", [
    ("ADD", [3, 5], (3 + 5)),
    ("ADD", [M - 1, 2], 1),
    ("SUB", [3, 5], 5 - 3 + M),     # stack top is first operand
    ("MUL", [7, 9], 63),
    ("DIV", [2, 10], 5),
    ("DIV", [0, 10], 0),
    ("SDIV", [M - 2, 10], M - 5),    # 10 / -2 = -5
    ("MOD", [3, 10], 1),
    ("MOD", [0, 10], 0),
    ("SMOD", [3, M - 10], M - 1),    # -10 smod 3 = -1
    ("ADDMOD", [5, M - 1, M - 1], (((M - 1) + (M - 1)) % 5)),
    ("MULMOD", [5, M - 1, M - 1], (((M - 1) * (M - 1)) % 5)),
    ("EXP", [3, 2], 8),
    ("SIGNEXTEND", [0xFF, 0], M - 1),   # stack: value below, byte-index on top
    ("SIGNEXTEND", [0x7F, 0], 0x7F),
    ("LT", [5, 3], 1),
    ("GT", [5, 3], 0),
    ("SLT", [1, M - 1], 1),          # -1 < 1
    ("SGT", [1, M - 1], 0),
    ("EQ", [4, 4], 1),
    ("ISZERO", [0], 1),
    ("AND", [0b1100, 0b1010], 0b1000),
    ("OR", [0b1100, 0b1010], 0b1110),
    ("XOR", [0b1100, 0b1010], 0b0110),
    ("NOT", [0], M - 1),
    ("BYTE", [0xAABB, 31], 0xBB),
    ("BYTE", [0xAABB, 30], 0xAA),
    ("BYTE", [0xAABB, 32], 0),
    ("SHL", [1, 4], 16),
    ("SHR", [16, 4], 1),
    ("SAR", [M - 16, 4], M - 1),
    ("SHL", [1, 256], 0),
])
def test_binary_ops(op, inputs, expected):
    state = make_state()
    push(state, *inputs)
    result = run(state, op)
    assert len(result) == 1
    top = result[0].mstate.stack[-1]
    assert top.raw.is_const, f"{op} result symbolic: {top}"
    assert top.value == expected % M


def test_stack_ops():
    state = make_state()
    push(state, 1, 2, 3)
    state = run(state, "DUP2")[0]
    assert state.mstate.stack[-1].value == 2
    state = run(state, "SWAP3")[0]
    assert state.mstate.stack[-1].value == 1
    state = run(state, "POP")[0]
    assert len(state.mstate.stack) == 3


def test_memory_roundtrip():
    state = make_state()
    push(state, 0xDEADBEEF, 64)  # value, offset
    state = run(state, "MSTORE")[0]
    push(state, 64)
    state = run(state, "MLOAD")[0]
    assert state.mstate.stack[-1].value == 0xDEADBEEF
    assert state.mstate.memory_size >= 96


def test_mstore8():
    state = make_state()
    push(state, 0x1234, 10)
    state = run(state, "MSTORE8")[0]
    assert state.mstate.memory[10].value == 0x34


def test_storage_roundtrip():
    state = make_state()
    push(state, 99, 5)  # value, key
    state = run(state, "SSTORE")[0]
    push(state, 5)
    state = run(state, "SLOAD")[0]
    assert state.mstate.stack[-1].value == 99


def test_sstore_static_protection():
    state = make_state(static=True)
    push(state, 99, 5)
    with pytest.raises(WriteProtection):
        run(state, "SSTORE")


def test_transient_storage():
    state = make_state()
    push(state, 77, 3)
    state = run(state, "TSTORE")[0]
    push(state, 3)
    state = run(state, "TLOAD")[0]
    assert state.mstate.stack[-1].value == 77


def test_call_to_cheat_address_succeeds():
    """hevm/forge cheat-code address is modeled as unconditional success
    (core/cheat_code.py) so foundry test scaffolding never blocks analysis."""
    from mythril_tpu.core.cheat_code import hevm_cheat_code

    state = make_state()
    # CALL args (pushed in reverse): retSize, retOff, argSize, argOff, value,
    # to, gas
    push(state, 0, 0, 0, 0, 0, hevm_cheat_code.address, 50000)
    successors = run(state, "CALL")
    assert len(successors) == 1
    retval = successors[0].mstate.stack[-1]
    constraints = successors[0].world_state.constraints
    assert any(c.raw.op == "eq"
               and retval.raw in c.raw.args
               and any(a.is_const and a.value == 1 for a in c.raw.args)
               for c in constraints), "retval must be pinned to success"
    assert not retval.raw.is_const  # symbolic retval constrained, not literal


def test_jumpi_forks_two_ways():
    # code: PUSH1 01 PUSH1 06 JUMPI STOP JUMPDEST STOP -> JUMPDEST at byte 6
    state = make_state("0x6001600657005b00")
    condition = symbol_factory.BitVecSym("cond", 256)
    state.mstate.stack.append(condition)              # condition (symbolic)
    state.mstate.stack.append(symbol_factory.BitVecVal(6, 256))  # dest
    states = run(state, "JUMPI")
    assert len(states) == 2
    fallthrough, taken = states
    assert fallthrough.mstate.pc == state.mstate.pc + 1
    jumpdest_index = state.environment.code.index_of_address(6)
    assert taken.mstate.pc == jumpdest_index
    assert len(taken.world_state.constraints) == 1


def test_jumpi_concrete_condition_single_branch():
    state = make_state("0x6001600657005b00")
    push(state, 1, 6)  # condition=1, dest=6
    states = run(state, "JUMPI")
    assert len(states) == 1
    assert states[0].mstate.pc == state.environment.code.index_of_address(6)


def test_invalid_jump_rejected():
    from mythril_tpu.core.util import InvalidJumpDestination

    state = make_state("0x600456005b00")
    push(state, 3)  # byte 3 is not a JUMPDEST
    with pytest.raises(InvalidJumpDestination):
        run(state, "JUMP")


def test_sha3_concrete():
    from mythril_tpu.utils.keccak import keccak256

    state = make_state()
    push(state, 0xAB, 0)
    state = run(state, "MSTORE8")[0]
    push(state, 1, 0)  # size=1, offset=0
    state = run(state, "SHA3")[0]
    assert state.mstate.stack[-1].value == int.from_bytes(keccak256(b"\xab"), "big")


def test_sha3_symbolic_goes_through_uf():
    state = make_state()
    state.mstate.memory[0] = symbol_factory.BitVecSym("mystery", 8)
    push(state, 1, 0)
    state = run(state, "SHA3")[0]
    assert not state.mstate.stack[-1].raw.is_const
    from mythril_tpu.core.function_managers import keccak_function_manager

    assert keccak_function_manager.create_conditions()  # axioms got registered


def test_calldata_ops():
    state = make_state(calldata=ConcreteCalldata("1", [0xAA, 0xBB]))
    push(state, 0)
    state = run(state, "CALLDATALOAD")[0]
    assert state.mstate.stack[-1].value >> 240 == 0xAABB
    state = run(state, "CALLDATASIZE")[0]
    assert state.mstate.stack[-1].value == 2


def test_env_ops():
    state = make_state()
    for op, expected in [("ADDRESS", 0x1AAF), ("CALLER", 0xCAFE),
                         ("ORIGIN", 0xCAFE), ("CALLVALUE", 0),
                         ("BASEFEE", 7), ("CHAINID", 1)]:
        result = run(state, op)[0]
        assert result.mstate.stack.pop().value == expected, op


def test_selfbalance_and_balance():
    state = make_state()
    state = run(state, "SELFBALANCE")[0]
    assert state.mstate.stack[-1].value == 10 ** 18


def test_invalid_opcode():
    state = make_state()
    with pytest.raises(InvalidInstruction):
        run(state, "INVALID")


def test_stop_raises_end_signal():
    from mythril_tpu.core.transaction import TransactionEndSignal

    state = make_state()
    with pytest.raises(TransactionEndSignal):
        run(state, "STOP")


def test_push_truncated_immediate():
    state = make_state()
    code = Disassembly("0x61aa")  # PUSH2 with one byte: pads right
    state.environment.code = code
    state.environment.active_account.code = code
    instruction = Instruction("PUSH2")
    states = instruction.evaluate(state)
    assert states[0].mstate.stack[-1].value == 0xAA00
