"""Scripted stand-in for ``python -m mythril_tpu.serve.worker``.

Speaks the supervisor's JSON-lines protocol (ready / heartbeat /
result) without importing mythril_tpu — supervisor unit tests spawn it
via the ``worker_argv`` override so death detection, retry, backoff,
and quarantine are exercised in milliseconds instead of paying a jax
import per worker.

Behavior is driven by the job itself:

* ``job["inject"]`` (set by the supervisor's fault plan) dies for real:
  SIGSEGV / SIGKILL to self, or going silent for ``worker_hang``;
* ``params["fake"]``: ``"exit3"`` exits with status 3 (plain
  WORKER_CRASH), ``"clean_error"`` answers ``ok: false`` (a surviving
  sandbox), ``"slow"`` emits ``params["beats"]`` heartbeats
  ``params["beat_s"]`` apart before answering — long enough jobs only
  survive because heartbeats reset the supervisor's deadline;
* anything else answers ``ok: true`` with a payload echoing the job, so
  tests can assert which dispatch (first try, ladder retry, resume
  retry) produced the answer.
"""

import json
import os
import signal
import sys
import time


def _send(**record):
    sys.stdout.write(json.dumps(record) + "\n")
    sys.stdout.flush()


def main() -> int:
    _send(event="ready", pid=os.getpid(), warmed=0)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        job = json.loads(line)
        if job.get("kind") == "shutdown":
            break
        job_id = job.get("job_id")
        inject = job.get("inject")
        if inject == "worker_segv":
            signal.signal(signal.SIGSEGV, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGSEGV)
        elif inject == "worker_oom":
            os.kill(os.getpid(), signal.SIGKILL)
        elif inject == "worker_hang":
            while True:
                time.sleep(3600)
        if job.get("kind") == "fleet":
            _send(event="result", job_id=job_id, ok=True,
                  payload={"outcomes": [
                      {"ok": True,
                       "payload": {"issue_count": 0, "member": index,
                                   "ladder": bool(job.get("ladder"))}}
                      for index, _ in enumerate(job.get("members") or [])]})
            continue
        params = job.get("params") or {}
        behavior = params.get("fake")
        if behavior == "exit3":
            return 3
        if behavior == "clean_error":
            _send(event="result", job_id=job_id, ok=False,
                  error_type="ValueError", error="clean in-worker failure")
            continue
        if behavior == "slow":
            for _ in range(int(params.get("beats", 3))):
                _send(event="heartbeat", job_id=job_id)
                time.sleep(float(params.get("beat_s", 0.2)))
        _send(event="result", job_id=job_id, ok=True,
              payload={"issue_count": 0, "pid": os.getpid(),
                       "params": params, "retry": bool(job.get("retry")),
                       "ladder": bool(job.get("ladder")),
                       "resume": job.get("resume"),
                       "serve_metrics": {"cold_buckets": 1, "warm_hits": 2,
                                         "frontier": {}}})
    return 0


if __name__ == "__main__":
    sys.exit(main())
