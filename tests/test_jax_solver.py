"""Differential tests: batched JAX DPLL (parallel/jax_solver.py) vs the native
CDCL core / Python DPLL on the same CNF, plus end-to-end `--solver jax` runs
through the full QF_ABV pipeline (lower -> blast -> solve)."""

import random

import pytest

jax = pytest.importorskip("jax")

from mythril_tpu.parallel import jax_solver  # noqa: E402
from mythril_tpu.smt import symbol_factory  # noqa: E402
from mythril_tpu.smt.solver import sat  # noqa: E402
from mythril_tpu.smt.solver.solver import Solver, check_formulas  # noqa: E402
from mythril_tpu.support.support_args import args  # noqa: E402


def _check_model(clauses, model):
    for clause in clauses:
        assert any((model[abs(l) - 1] if l > 0 else not model[abs(l) - 1])
                   for l in clause), f"clause {clause} unsatisfied"


def _random_cnf(rng, n_vars, n_clauses, k=3):
    return [[rng.choice([-1, 1]) * rng.randint(1, n_vars)
             for _ in range(rng.randint(1, k))]
            for _ in range(n_clauses)]


def test_trivial():
    status, model = jax_solver.solve_cnf_device([[1], [2, -1]], 2)
    assert status == jax_solver.SAT
    _check_model([[1], [2, -1]], model)

    status, _ = jax_solver.solve_cnf_device([[1], [-1]], 1)
    assert status == jax_solver.UNSAT


def test_random_cnf_differential():
    rng = random.Random(7)
    agree = 0
    for trial in range(30):
        n_vars = rng.randint(3, 24)
        # around the sat/unsat phase transition so both verdicts appear
        n_clauses = int(n_vars * rng.uniform(2.0, 6.0))
        clauses = _random_cnf(rng, n_vars, n_clauses)
        ref_status, _ = sat.solve_cnf(clauses, n_vars)
        dev_status, dev_model = jax_solver.solve_cnf_device(
            clauses, n_vars, n_probes=8, max_steps=50_000)
        assert dev_status != jax_solver.UNKNOWN, f"trial {trial} unknown"
        assert dev_status == ref_status, f"trial {trial} verdict mismatch"
        if dev_status == jax_solver.SAT:
            _check_model(clauses, dev_model)
        agree += 1
    assert agree == 30


def test_long_clauses_split():
    # one long clause + forcing units; exercises the connector-splitting path
    clauses = [[-1], [-2], [-3], [-4], [1, 2, 3, 4, 5]]
    status, model = jax_solver.solve_cnf_device(clauses, 5)
    assert status == jax_solver.SAT
    assert model[4] is True

    clauses = [[-1], [-2], [-3], [-4], [-5], [1, 2, 3, 4, 5]]
    status, _ = jax_solver.solve_cnf_device(clauses, 5)
    assert status == jax_solver.UNSAT


def test_implication_chain_backtracking_regression():
    """ADVICE r2 high: duplicate-index trail scatter dropped implied literals,
    so stale assignments survived backtracking and this SAT instance was
    reported UNSAT by the device solver."""
    clauses = [[1, 2], [1, -2, 3], [-3, -2, 1], [-2, -1], [4, 1, 2]]
    ref_status, _ = sat.solve_cnf(clauses, 4)
    assert ref_status == sat.SAT
    status, model = jax_solver.solve_cnf_device(clauses, 4, n_probes=1)
    assert status == jax_solver.SAT
    _check_model(clauses, model)


def test_empty_cnf_is_sat():
    """ADVICE r2 medium: the zero-row padding used to act as an empty
    (always-false) clause, reporting UNSAT for a trivially-true problem."""
    status, model = jax_solver.solve_cnf_device([], 3)
    assert status == jax_solver.SAT
    assert model == [False, False, False]


def test_empty_clause_is_unsat():
    status, _ = jax_solver.solve_cnf_device([[1], []], 1)
    assert status == jax_solver.UNSAT


def test_clause_cap_returns_unknown():
    """Problems above the device clause cap must refuse (UNKNOWN), never
    crash or guess — the solver seam then falls back to CDCL loudly."""
    clauses = [[1, 2], [-1, 2]] * 40
    status, _ = jax_solver.solve_cnf_device(clauses, 2, clause_cap=10)
    assert status == jax_solver.UNKNOWN


def test_device_failure_falls_back_to_cdcl(monkeypatch):
    """VERDICT r2 weak #1: a TPU-side failure silently produced a clean
    report. The seam must catch, count, and re-solve on the CDCL core."""
    from mythril_tpu.smt.solver import solver as solver_module
    from mythril_tpu.smt.solver.incremental import IncrementalPipeline
    from mythril_tpu.smt.solver.solver_statistics import SolverStatistics

    def boom(*a, **k):
        raise RuntimeError("TPU worker process crashed")

    monkeypatch.setattr(jax_solver, "solve_cnf_device", boom)
    # a fresh pipeline: the process-wide pool may exceed the device clause
    # cap (the seam then skips the device entirely and never hits the crash)
    if sat.have_native():
        monkeypatch.setattr(solver_module, "_pipeline", IncrementalPipeline())
    stats = SolverStatistics()
    before = stats.device_fallbacks
    a = symbol_factory.BitVecSym("fb", 32)
    args.solver = "jax"
    try:
        solver = Solver(timeout=20_000)
        solver.add(a == 5)
        assert solver.check() == "sat"
        assert solver.model().eval((a == 5).raw)
    finally:
        args.solver = "cdcl"
    assert stats.device_fallbacks == before + 1


def test_realistic_multiply_query_no_crash():
    """The r2 crash repro: a 256-bit multiply bit-blasts to ~1e5 clauses; the
    monolithic gather killed the TPU worker. Now the cap routes it to CDCL
    and the verdict/model must still be correct under --solver jax."""
    x = symbol_factory.BitVecSym("mulx", 256)
    y = symbol_factory.BitVecSym("muly", 256)
    args.solver = "jax"
    try:
        solver = Solver(timeout=60_000)
        solver.add(x * y == 12, x > 1, y > 1)
        assert solver.check() == "sat"
        model = solver.model()
        xv = model.eval(x.raw)
        yv = model.eval(y.raw)
        assert (xv * yv) % (1 << 256) == 12
    finally:
        args.solver = "cdcl"


def test_pipeline_with_jax_backend():
    """Full QF_BV queries through Solver with --solver jax."""
    a = symbol_factory.BitVecSym("a", 32)
    b = symbol_factory.BitVecSym("b", 32)
    cases_sat = [
        [a + b == 100, a > 10, b > 10],
        [a * symbol_factory.BitVecVal(3, 32) == 99],
        [(a & 0xFF) == 0x42, a > 1000],
    ]
    cases_unsat = [
        [a > b, b > a],
        [a == 5, a == 6],
        [a + 1 < a, a == 0],
    ]
    args.solver = "jax"
    try:
        for constraints in cases_sat:
            solver = Solver(timeout=20_000)
            solver.add(*constraints)
            assert solver.check() == "sat"
            model = solver.model()
            for c in constraints:
                assert model.eval(c.raw)
        for constraints in cases_unsat:
            solver = Solver(timeout=20_000)
            solver.add(*constraints)
            assert solver.check() == "unsat"
    finally:
        args.solver = "cdcl"


def test_sharded_clause_matrix_verdicts_match_single_device(monkeypatch):
    """SURVEY 2.3 TP analogue: the clause matrix shards across the 8-device
    CPU mesh (unit-prop verdicts combined with pmax collectives); verdicts
    must match the single-device runner on problems big enough to shard
    (>= 8 clause tiles, i.e. > 7*2048 clauses)."""
    import jax
    import numpy as np

    if len(jax.devices()) < 2:
        pytest.skip("needs the conftest's 8-device CPU mesh")
    monkeypatch.setenv("MYTHRIL_TPU_SHARD", "1")

    rng = np.random.default_rng(7)
    n_vars = 400

    def random_cnf(planted):
        # planted-solution 3-SAT: each clause satisfied by `planted`
        clauses = []
        for _ in range(8 * jax_solver.TILE + 5):
            vs = rng.choice(n_vars, size=3, replace=False) + 1
            signs = rng.integers(0, 2, size=3) * 2 - 1
            clause = [int(v * s) for v, s in zip(vs, signs)]
            if planted is not None and not any(
                    (lit > 0) == planted[abs(lit) - 1] for lit in clause):
                # flip one literal to agree with the planted assignment
                clause[0] = (abs(clause[0])
                             if planted[abs(clause[0]) - 1]
                             else -abs(clause[0]))
            clauses.append(clause)
        return clauses

    planted = [bool(b) for b in rng.integers(0, 2, size=n_vars)]
    sat_clauses = random_cnf(planted)
    status, model = jax_solver.solve_cnf_device(sat_clauses, n_vars,
                                                max_steps=60_000)
    assert status == jax_solver.SAT
    for clause in sat_clauses:
        assert any((lit > 0) == model[abs(lit) - 1] for lit in clause)

    # UNSAT: pin a variable both ways on top of a big satisfiable matrix
    unsat_clauses = sat_clauses + [[n_vars + 1], [-(n_vars + 1)]]
    status, _ = jax_solver.solve_cnf_device(unsat_clauses, n_vars + 1,
                                            max_steps=60_000)
    assert status == jax_solver.UNSAT
