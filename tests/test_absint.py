"""Value-range + memory-region abstract interpretation
(``staticanalysis/absint.py``) and its consumers:

* soundness: a concrete differential reference on random branchy
  programs — every concrete stack cell observed at a block entry must
  lie inside the computed stride-interval, and every concrete memory
  write must land inside the block's proven write region (``None`` =
  ⊤ claims nothing and is always sound);
* widening: an unbounded counting loop must still converge, with the
  header interval absorbing every concrete counter value;
* the consumer surface: proven loop trip bounds
  (``cfa_screen.loop_bound_at`` -> ``core/strategy/bounded_loops.py``),
  constant-JUMPI verdicts, join write regions and their 32-byte merge
  windows (``parallel/frontier.py`` -> ``symstep.merge_pass``);
* the knobs: ``MYTHRIL_TPU_ABSINT`` / ``_MAX_ITERS`` / ``_MEM_REGIONS``
  gate the pass exactly as the README table declares;
* the device kernel: a diamond whose arms both MSTORE different words
  at offset 0 is blocked by the identical-memory gate (counted in
  ``frontier.merge.blocked_by.memory``) and merged by the widened
  phase when the static window table unlocks it — with byte-identical
  detections either way (the ``--no-absint`` A/B contract).
"""

import os
import random
import sys

import pytest

os.environ.setdefault("MYTHRIL_TPU_LANES", "16")

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mythril_tpu.frontends.asm import assemble  # noqa: E402
from mythril_tpu.frontends.disassembler import Disassembly  # noqa: E402
from mythril_tpu.staticanalysis import (build_absint,  # noqa: E402
                                        build_cfa, get_absint)
from mythril_tpu.staticanalysis.absint import (AbsintResult,  # noqa: E402
                                               contains)

_WORD = (1 << 256) - 1


def _build(asm_source):
    disassembly = Disassembly(assemble(asm_source).hex())
    cfa = build_cfa(disassembly)
    assert cfa is not None
    result = build_absint(disassembly, cfa)
    assert result is not None
    return disassembly, cfa, result


# -- the concrete differential reference ---------------------------------------------
#
# Random two-armed diamonds over a modeled opcode subset. A concrete
# run picks one arm per calldata seed; the fixpoint must cover BOTH.
# Any concrete stack cell outside its interval, or any concrete write
# outside its region, is a domain-transfer bug.

_BINARY = {
    "ADD": lambda a, b: (a + b) & _WORD,
    "SUB": lambda a, b: (a - b) & _WORD,
    "MUL": lambda a, b: (a * b) & _WORD,
    "AND": lambda a, b: a & b,
    "OR": lambda a, b: a | b,
    "XOR": lambda a, b: a ^ b,
}


def _random_arm(rng):
    """Stack-valid random straight-line op list (op, push arg)."""
    ops = []
    depth = 0
    for _ in range(rng.randint(4, 12)):
        pool = ["PUSH1"]
        if depth >= 1:
            pool += ["CALLDATALOAD", "DUP1", "STORE8"]
        if depth >= 2:
            pool += list(_BINARY) + ["DUP2", "SWAP1", "STORE"]
        if depth >= 3:
            pool += ["POP", "WILDSTORE"]
        op = rng.choice(pool)
        if op == "STORE":
            # constant-offset MSTORE of whatever is on the stack —
            # the bounded-region path
            ops.append(("PUSH1", rng.choice((0, 32, 64, 96))))
            ops.append(("MSTORE", None))
            depth -= 1
        elif op == "STORE8":
            ops.append(("PUSH1", rng.randint(0, 127)))
            ops.append(("MSTORE8", None))
            depth -= 1
        elif op == "WILDSTORE":
            # data-dependent offset: the pass must go ⊤, not guess
            ops.append(("MSTORE", None))
            depth -= 2
        else:
            ops.append((op, rng.randint(0, 255) if op == "PUSH1"
                        else None))
            if op in ("PUSH1", "CALLDATALOAD", "DUP1", "DUP2"):
                depth += 1 if op != "CALLDATALOAD" else 0
            elif op in _BINARY or op == "POP":
                depth -= 1
    return ops


def _render(ops):
    return "\n".join(f"PUSH1 {arg:#04x}" if op == "PUSH1" else op
                     for op, arg in ops)


def _random_program(rng):
    """A two-armed diamond around random arm bodies."""
    return (
        "PUSH1 0x00\nCALLDATALOAD\nPUSH @odd\nJUMPI\n"
        + _render(_random_arm(rng))
        + "\nPUSH @join\nJUMP\nodd:\nJUMPDEST\n"
        + _render(_random_arm(rng))
        + "\njoin:\nJUMPDEST\nSTOP\n")


def _calldata(seed, offset):
    return (seed * 1000003 + offset * 7919 + 11) & _WORD


def _imm(instruction):
    return int(instruction.argument, 16)


def _run_concrete(disassembly, cfa, seed, max_steps=4096):
    """Concretely execute the contract; returns

    * ``entries`` — (block id, stack snapshot bottom->top) at every
      block-entry arrival,
    * ``writes`` — (block id, offset, size) per memory write.
    """
    by_address = {ins.address: i
                  for i, ins in enumerate(disassembly.instruction_list)}
    stack, entries, writes = [], [], []
    index = 0
    for _ in range(max_steps):
        ins = disassembly.instruction_list[index]
        block_id = cfa.block_at(ins.address)
        if block_id is not None \
                and cfa.blocks[block_id].start_pc == ins.address:
            entries.append((block_id, tuple(stack)))
        op = ins.op_code
        if op == "STOP":
            return entries, writes
        if op.startswith("PUSH"):
            stack.append(_imm(ins))
        elif op == "CALLDATALOAD":
            stack.append(_calldata(seed, stack.pop()))
        elif op in _BINARY:
            a, b = stack.pop(), stack.pop()
            stack.append(_BINARY[op](a, b))
        elif op == "DUP1":
            stack.append(stack[-1])
        elif op == "DUP2":
            stack.append(stack[-2])
        elif op == "SWAP1":
            stack[-1], stack[-2] = stack[-2], stack[-1]
        elif op == "POP":
            stack.pop()
        elif op == "MSTORE":
            offset, _value = stack.pop(), stack.pop()
            writes.append((block_id, offset, 32))
        elif op == "MSTORE8":
            offset, _value = stack.pop(), stack.pop()
            writes.append((block_id, offset, 1))
        elif op == "JUMP":
            index = by_address[stack.pop()]
            continue
        elif op == "JUMPI":
            dest, cond = stack.pop(), stack.pop()
            if cond:
                index = by_address[dest]
                continue
        elif op == "JUMPDEST":
            pass
        else:
            raise AssertionError(f"unmodeled op {op}")
        index += 1
    raise AssertionError("concrete run did not terminate")


def _assert_entry_sound(result, block_id, stack):
    assert block_id in result.entry_intervals, \
        f"block {block_id} reached concretely but not abstractly"
    height, vals = result.entry_intervals[block_id]
    if height is not None:
        assert len(stack) == height, \
            f"block {block_id}: concrete height {len(stack)} != " \
            f"abstract {height}"
        assert len(stack) >= len(vals)
    for cell in range(min(len(vals), len(stack))):
        iv, value = vals[-1 - cell], stack[-1 - cell]
        assert contains(iv, value), \
            f"block {block_id} cell -{cell + 1}: {value:#x} not in {iv}"


def _assert_write_sound(result, block_id, offset, size):
    regions = result.block_writes.get(block_id)
    if regions is None:
        return  # ⊤: no claim
    assert any(start <= offset and offset + size <= end
               for start, end in regions), \
        f"block {block_id}: write [{offset}, {offset + size}) " \
        f"outside proven {regions}"


def test_random_programs_intervals_are_sound():
    rng = random.Random(0xab51)
    for trial in range(40):
        disassembly, cfa, result = _build(_random_program(rng))
        for seed in (rng.getrandbits(64), rng.getrandbits(64) | 1):
            entries, writes = _run_concrete(disassembly, cfa, seed)
            assert entries, "no block entry observed"
            for block_id, stack in entries:
                _assert_entry_sound(result, block_id, stack)
            for block_id, offset, size in writes:
                _assert_write_sound(result, block_id, offset, size)


# -- widening / loop bounds ----------------------------------------------------------

#: i = 0; i += 1 forever — only widening terminates the fixpoint
UNBOUNDED_LOOP = """
PUSH1 0x00
head:
JUMPDEST
PUSH1 0x01
ADD
PUSH @head
JUMP
"""

#: i = 0; while i != 5: i += 1 — five iterations, six header arrivals
COUNTING_LOOP = """
PUSH1 0x00
head:
JUMPDEST
DUP1
PUSH1 0x05
EQ
PUSH @exit
JUMPI
PUSH1 0x01
ADD
PUSH @head
JUMP
exit:
JUMPDEST
POP
STOP
"""


def _header_pc(disassembly):
    for ins in disassembly.instruction_list:
        if ins.op_code == "JUMPDEST":
            return ins.address
    raise AssertionError("no loop header JUMPDEST")


def test_widening_converges_on_unbounded_loop():
    disassembly, cfa, result = _build(UNBOUNDED_LOOP)
    assert result.widenings >= 1
    assert result.iterations < 256  # far under the bail cap
    header_block = cfa.block_at(_header_pc(disassembly))
    _height, vals = result.entry_intervals[header_block]
    counter = vals[-1]
    # the widened interval absorbs every concrete counter value
    for value in (0, 1, 2, 1000, 10 ** 9):
        assert contains(counter, value)


def test_counting_loop_bound_is_proven():
    disassembly, _cfa, result = _build(COUNTING_LOOP)
    header = _header_pc(disassembly)
    assert result.loop_bounds == {header: 6}
    assert result.loop_bound(header) == 6
    assert result.loop_bound(header + 1) is None


def test_loop_bound_consumer_via_cfa_screen():
    from mythril_tpu.smt.solver import cfa_screen

    disassembly = Disassembly(assemble(COUNTING_LOOP).hex())
    header = _header_pc(disassembly)
    assert cfa_screen.loop_bound_at(disassembly, header) == 6


# -- constant-JUMPI verdicts ---------------------------------------------------------

ALWAYS_TAKEN = """
PUSH1 0x01
PUSH @live
JUMPI
PUSH1 0x00
PUSH1 0x00
REVERT
live:
JUMPDEST
STOP
"""

NEVER_TAKEN = """
PUSH1 0x00
PUSH @dead
JUMPI
STOP
dead:
JUMPDEST
PUSH1 0x00
PUSH1 0x00
REVERT
"""


def _jumpi_pc(disassembly):
    return next(ins.address for ins in disassembly.instruction_list
                if ins.op_code == "JUMPI")


def test_const_jumpi_verdicts():
    disassembly, _cfa, result = _build(ALWAYS_TAKEN)
    assert result.jumpi_verdict(_jumpi_pc(disassembly)) is True

    disassembly, _cfa, result = _build(NEVER_TAKEN)
    assert result.jumpi_verdict(_jumpi_pc(disassembly)) is False
    # no claim at a non-JUMPI pc
    assert result.jumpi_verdict(0) is None


# -- join regions and the 32-byte merge windows --------------------------------------

#: both diamond arms MSTORE a different word at offset 0 and push the
#: same stack value before the join
DIAMOND_ASM = """
PUSH1 0x00
CALLDATALOAD
PUSH @odd
JUMPI
PUSH1 0x07
PUSH1 0x00
MSTORE
PUSH1 0x05
PUSH @join
JUMP
odd:
JUMPDEST
PUSH1 0x09
PUSH1 0x00
MSTORE
PUSH1 0x05
join:
JUMPDEST
POP
STOP
"""


def test_diamond_join_region_and_windows():
    disassembly, cfa, result = _build(DIAMOND_ASM)
    assert cfa.branch_merge_pc, "diamond join not recovered"
    join_pc = next(iter(cfa.branch_merge_pc.values()))
    assert result.join_regions[join_pc] == ((0, 32),)
    assert result.word_windows(join_pc) == (0,)
    assert result.word_windows(join_pc + 1) is None  # untracked pc
    assert result.regions_proven == 1


def _windows_only(join_regions, cap=8):
    return AbsintResult(
        code_length=0, entry_intervals={}, block_writes={},
        join_regions=join_regions, loop_bounds={}, const_jumpis={},
        mem_regions_cap=cap)


def test_word_windows_never_overlap():
    # nearby regions must share one cursor: naive per-region rounding
    # would emit overlapping windows and break the kernel's
    # diff-containment equality
    result = _windows_only({7: ((0, 8), (16, 40))})
    assert result.word_windows(7) == (0, 32)
    result = _windows_only({7: ((4, 40),)})
    assert result.word_windows(7) == (4, 36)


def test_word_windows_cap_is_top():
    spread = tuple((64 * k, 64 * k + 8) for k in range(12))
    assert _windows_only({7: spread}, cap=8).word_windows(7) is None
    assert _windows_only({7: spread}, cap=16).word_windows(7) == \
        tuple(64 * k for k in range(12))


# -- persistence ---------------------------------------------------------------------

def test_json_roundtrip():
    _disassembly, cfa, result = _build(DIAMOND_ASM)
    join_pc = next(iter(cfa.branch_merge_pc.values()))
    clone = AbsintResult.from_json(result.to_json())
    assert clone is not None
    assert clone.entry_intervals == result.entry_intervals
    assert clone.block_writes == result.block_writes
    assert clone.join_regions == result.join_regions
    assert clone.loop_bounds == result.loop_bounds
    assert clone.const_jumpis == result.const_jumpis
    assert clone.word_windows(join_pc) == result.word_windows(join_pc)


def test_from_json_rejects_malformed_documents():
    assert AbsintResult.from_json(None) is None
    assert AbsintResult.from_json([]) is None
    assert AbsintResult.from_json({"version": -1}) is None


# -- the env knobs -------------------------------------------------------------------

def test_absint_flag_gates_the_pass(monkeypatch):
    from mythril_tpu.smt.solver import cfa_screen

    monkeypatch.setenv("MYTHRIL_TPU_ABSINT", "0")
    assert not cfa_screen.absint_enabled()
    disassembly = Disassembly(assemble(DIAMOND_ASM).hex())
    assert get_absint(disassembly) is None
    assert cfa_screen.jumpi_verdict(disassembly, 0) is None
    assert cfa_screen.merge_mem_windows(disassembly, 0) is None


def test_max_iters_knob_limits_loop_proofs(monkeypatch):
    # a 6-arrival loop cannot be proven with a 2-arrival budget
    monkeypatch.setenv("MYTHRIL_TPU_ABSINT_MAX_ITERS", "2")
    disassembly = Disassembly(assemble(COUNTING_LOOP).hex())
    result = build_absint(disassembly)
    assert result is not None
    assert result.loop_bounds == {}


def test_mem_regions_knob_caps_the_windows(monkeypatch):
    monkeypatch.setenv("MYTHRIL_TPU_ABSINT_MEM_REGIONS", "1")
    disassembly = Disassembly(assemble(DIAMOND_ASM).hex())
    result = build_absint(disassembly)
    assert result is not None
    assert result.mem_regions_cap == 1


# -- the device kernel: widened memory-plane merging ---------------------------------

#: the both-arms-write diamond: JUMPI forks at pc 5; the fall arm
#: MSTOREs 7 at offset 0, the taken arm MSTOREs 9 — both push 5 and
#: reach the join JUMPDEST@25 after six steps (padding equalizes the
#: arms), then spin 25 -> 26 -> 28 -> 25 staying RUNNING forever.
#: Stacks and msize agree at the join; ONLY memory bytes differ.
DIAMOND_BOTHWRITE = bytes.fromhex(
    "6000" "35"           # 0: PUSH1 0; CALLDATALOAD   (symbolic word)
    "6010" "57"           # 3: PUSH1 16; JUMPI         (fork)
    "6007" "6000" "52"    # 6: PUSH1 7; PUSH1 0; MSTORE   (fall arm)
    "6005" "6019" "56"    # 11: PUSH1 5; PUSH1 25; JUMP
    "5b" "6009"           # 16: JUMPDEST; PUSH1 9      (taken arm)
    "6000" "52"           # 19: PUSH1 0; MSTORE
    "6005"                # 22: PUSH1 5
    "5b"                  # 24: JUMPDEST               (padding)
    "5b" "6019" "56")     # 25: JUMPDEST; PUSH1 25; JUMP (join + spin)

STOP_ONLY = bytes.fromhex("00")


def _bothwrite_run(n_steps=13):
    import numpy as np

    from mythril_tpu.parallel import arena as parena
    from mythril_tpu.parallel import batch as pbatch
    from mythril_tpu.parallel import symstep

    specs = [pbatch.LaneSpec(DIAMOND_BOTHWRITE, gas_limit=2 ** 40),
             pbatch.LaneSpec(STOP_ONLY, gas_limit=2 ** 40)]
    state = pbatch.build_batch(specs, stack_slots=16, memory_bytes=128,
                               calldata_bytes=64, retdata_bytes=32,
                               storage_slots=8, tstore_slots=2)
    planes = symstep.SymPlanes.empty(2, 16, 128, 8, max_conds=8)
    arena = parena.new_arena(capacity=1 << 10, const_capacity=1 << 6)
    sched = symstep.new_scheduler(state, planes, 4, 4)
    state, planes, arena, sched = symstep.run_chunk(
        state, planes, arena, sched, n_steps)
    assert (np.asarray(state.status) == symstep.RUNNING).sum() == 2
    np.testing.assert_array_equal(np.asarray(state.pc), [25, 25])
    return state, planes, arena


def _const_word(arena, node):
    import numpy as np

    from mythril_tpu.parallel import arena as parena

    assert int(np.asarray(arena.op)[node]) == parena.CONST
    limbs = np.asarray(arena.const_vals)[int(np.asarray(arena.imm)[node])]
    return sum(int(limb) << (16 * i) for i, limb in enumerate(limbs))


def _native_cdcl():
    from mythril_tpu.smt.solver import sat

    return sat.have_native()


def test_identical_memory_gate_blocks_and_counts():
    """Without a window table the pair must NOT merge, and the
    blocked-by accounting must attribute the refusal to memory."""
    pytest.importorskip("jax")
    import numpy as np

    from mythril_tpu.parallel import symstep

    state, planes, arena = _bothwrite_run()
    state, planes, arena, stats = symstep.merge_pass(
        state, planes, arena, np.asarray([25], dtype=np.int32),
        n_rounds=2)
    stats = np.asarray(stats)
    assert int(stats[0]) == 0                  # no merge
    blocked = dict(zip(symstep.MERGE_BLOCKED_LABELS, stats[3:8]))
    assert int(blocked["memory"]) == 1
    assert int(blocked["mem_sym"]) == 0
    assert (np.asarray(state.status) == symstep.RUNNING).sum() == 2


def test_window_table_unlocks_the_memory_blend():
    """The static window [0, 32) proves the divergence is containable:
    the widened phase must merge the pair, retiring one lane and
    rewriting the survivor's word as a clean per-byte ITE reference."""
    pytest.importorskip("jax")
    import numpy as np

    from mythril_tpu.parallel import symstep

    state, planes, arena = _bothwrite_run()
    state, planes, arena, stats = symstep.merge_pass(
        state, planes, arena, np.asarray([25], dtype=np.int32),
        mem_pcs=np.asarray([25], dtype=np.int32),
        mem_words=np.asarray([[0]], dtype=np.int32), n_rounds=2)
    stats = np.asarray(stats)
    assert int(stats[0]) == 1                  # merged
    assert int(stats[2]) == 1                  # one memory blend
    st = np.asarray(state.status)
    assert (st == symstep.RUNNING).sum() == 1
    assert (st == symstep.DEAD).sum() == 1
    survivor = int(np.argmax(st == symstep.RUNNING))
    # path condition popped: (P & c) | (P & ~c) = P
    assert int(np.asarray(planes.cond_count)[survivor]) == 0
    # the blended word: every byte cell points at ONE ITE node, in the
    # symbolic MSTORE's clean (node << 5) + j encoding
    cells = np.asarray(planes.mem_sym)[survivor, 0:32]
    first = int(cells[0])
    assert first > 0 and first % 32 == 0
    np.testing.assert_array_equal(cells,
                                  first + np.arange(32, dtype=cells.dtype))
    ite = first >> 5
    assert int(np.asarray(arena.op)[ite]) == 0x0F
    assert _const_word(arena, int(np.asarray(arena.b)[ite])) == 9
    assert _const_word(arena, int(np.asarray(arena.c)[ite])) == 7


# -- the full A/B contract: --no-absint is invisible to the detectors ----------------

#: branchy veritesting contract whose arms BOTH write memory: the
#: identical-memory gate blocks the join without absint, the widened
#: phase merges it with absint — detections must match either way
BRANCHY_MEM = {
    "boom()":
        "PUSH1 0x00\nCALLDATALOAD\nPUSH1 0x01\nAND\n"
        "PUSH @odd\nJUMPI\n"
        "PUSH1 0x07\nPUSH1 0x00\nMSTORE\nPUSH1 0x05\nPUSH @join\nJUMP\n"
        "odd:\nJUMPDEST\nPUSH1 0x09\nPUSH1 0x00\nMSTORE\nPUSH1 0x05\n"
        "JUMPDEST\n"
        "join:\nJUMPDEST\nPUSH1 0x00\nSSTORE\nJUMPDEST\n"
        "CALLER\nSELFDESTRUCT",
}


def _analyze_branchy_mem(absint_on, monkeypatch):
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import creation_wrapper, dispatcher
    from mythril_tpu.observe import metrics

    if not absint_on:
        monkeypatch.setenv("MYTHRIL_TPU_ABSINT", "0")
    monkeypatch.setenv("MYTHRIL_TPU_CHUNK", "1")
    metrics.reset("frontier.merge")
    metrics.reset("absint")
    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(BRANCHY_MEM)))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30, transaction_count=1,
        modules=["AccidentallyKillable"], compulsory_statespace=False,
        engine="tpu")
    issues = fire_lasers(wrapper, white_list=["AccidentallyKillable"])
    detections = sorted(
        (issue.swc_id, issue.address, issue.function,
         [step.get("input") for step in
          issue.transaction_sequence["steps"]])
        for issue in issues)
    return detections, metrics.snapshot()


def test_absint_ab_detections_identical(monkeypatch):
    """The tentpole acceptance: with absint the widened phase merges a
    memory-diverged pair the identical-memory gate blocks, and the
    detectors cannot tell the difference. Witness calldata is compared
    by selector (the merged path's weaker disjunction may pick another
    valid model for the unconstrained branch word)."""
    pytest.importorskip("jax")
    if not _native_cdcl():
        pytest.skip("native CDCL build required")

    with_absint, snap_on = _analyze_branchy_mem(True, monkeypatch)
    without, snap_off = _analyze_branchy_mem(False, monkeypatch)

    def norm(detections):
        return [(swc, addr, fn, [step[:10] for step in steps])
                for swc, addr, fn, steps in detections]

    assert norm(with_absint) == norm(without)
    assert [d[0] for d in with_absint] == ["106"]
    # absint on: the widened phase actually blended a memory plane
    assert snap_on.get("absint.merge.mem_blends", 0) >= 1
    assert snap_on.get("frontier.merge.events", 0) >= 1
    # absint off: the same join was blocked by the memory gate
    assert snap_off.get("absint.merge.mem_blends", 0) == 0
    assert snap_off.get("frontier.merge.blocked_by.memory", 0) >= 1
