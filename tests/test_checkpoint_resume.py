"""Host-phase checkpoint/resume (VERDICT r3 missing #4 / next-round #6).

The reference has no engine checkpointing at all (SURVEY §5); round 3 added
device-phase .npz snapshots only, so a killed `--bin-runtime` analysis (pure
host) lost everything. These tests cut an analysis mid-way at a transaction
boundary — exactly what a kill between transactions leaves on disk — and
assert the resumed run emits the identical issue set.
"""

import os
import pickle
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontends.asm import assemble, creation_wrapper, dispatcher
from mythril_tpu.smt.solver import sat

pytestmark = pytest.mark.skipif(not sat.have_native(),
                                reason="native CDCL build required")


def _analyze(tx_count, modules, checkpoint=None, resume=None):
    from test_analysis import KILLBILLY

    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(KILLBILLY)))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30,
        transaction_count=tx_count, modules=modules,
        compulsory_statespace=False, checkpoint_path=checkpoint,
        resume_path=resume)
    return fire_lasers(wrapper, white_list=modules)


def test_resume_from_tx_boundary_finds_identical_issues(tmp_path):
    """Cut after tx1 (the state a kill between transactions leaves), resume
    into tx2: the 2-tx selfdestruct chain must still be found, identical to
    the uninterrupted run."""
    modules = ["AccidentallyKillable"]
    full = _analyze(2, modules)
    assert sorted(i.swc_id for i in full) == ["106"]

    ckpt = str(tmp_path / "analysis.ckpt")
    partial = _analyze(1, modules, checkpoint=ckpt)
    assert partial == []  # 1 tx cannot reach the selfdestruct
    assert os.path.exists(ckpt)

    resumed = _analyze(2, modules, resume=ckpt)
    assert sorted(i.swc_id for i in resumed) == \
        sorted(i.swc_id for i in full) == ["106"]
    # witness parity, not just SWC-id parity
    assert resumed[0].transaction_sequence["steps"][-1]["input"] == \
        full[0].transaction_sequence["steps"][-1]["input"]


def test_checkpoint_payload_roundtrip(tmp_path):
    """The pickle payload must restore worklist/open-state structure exactly
    (terms re-intern: identity-equality survives the round-trip)."""
    from mythril_tpu.support import checkpoint as cp

    modules = ["AccidentallyKillable"]
    ckpt = str(tmp_path / "payload.ckpt")
    _analyze(1, modules, checkpoint=ckpt)
    payload = cp.load_host_checkpoint(ckpt)
    assert payload is not None
    assert payload["tx_index"] == 1
    assert payload["open_states"], "no open states captured"
    state = payload["open_states"][0]
    for constraint in state.constraints:
        reloaded = pickle.loads(pickle.dumps(constraint.raw))
        assert reloaded is constraint.raw  # hash-consing identity preserved


def test_corrupt_checkpoint_degrades_to_fresh_run(tmp_path):
    ckpt = tmp_path / "garbage.ckpt"
    ckpt.write_bytes(b"not a pickle")
    modules = ["AccidentallyKillable"]
    issues = _analyze(2, modules, resume=str(ckpt))
    assert sorted(i.swc_id for i in issues) == ["106"]
