"""Tier-1 wiring for tools/check_excepts.py — now a back-compat shim over
the tpu-lint rules R1/R2 (tools/lint/). These tests pin the historical
surface (check_file/check_device_calls/run/ALLOWLIST and the legacy
violation-tuple shape) so existing CI wiring keeps working; the rules
themselves, the other rules R3-R5, and the framework plumbing are covered
by tests/test_lint.py."""

import os
import sys

import pytest

TOOLS_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "tools"))
sys.path.insert(0, TOOLS_DIR)

import check_excepts  # noqa: E402


def test_no_silent_blanket_excepts():
    violations = check_excepts.run()
    assert not violations, "\n".join(
        f"{path}:{lineno}: {detail}" for path, lineno, detail in violations)


def test_allowlist_entries_still_exist():
    """A stale allowlist entry (file refactored, function renamed) would let
    a future swallow sneak in under the dead key — every entry must still
    point at a real silent-blanket site."""
    live = set()
    for scan_dir in check_excepts.SCAN_DIRS:
        base = os.path.join(check_excepts.REPO_ROOT, scan_dir)
        for dirpath, _, filenames in os.walk(base):
            for filename in sorted(filenames):
                if not filename.endswith(".py"):
                    continue
                path = os.path.join(dirpath, filename)
                relpath = os.path.relpath(
                    path, check_excepts.REPO_ROOT).replace(os.sep, "/")
                import ast

                with open(path, encoding="utf-8") as handle:
                    tree = ast.parse(handle.read(), filename=path)
                for node in ast.walk(tree):
                    if isinstance(node, ast.ExceptHandler) and \
                            check_excepts._is_broad(node) and \
                            check_excepts._is_silent(node):
                        live.add((relpath,
                                  check_excepts._enclosing_function(tree,
                                                                    node)))
    stale = check_excepts.ALLOWLIST - live
    assert not stale, f"stale allowlist entries: {sorted(stale)}"


def test_detects_violation(tmp_path):
    """The linter actually fires on the pattern it claims to ban."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n")
    violations = check_excepts.check_file(str(bad))
    assert len(violations) == 1
    assert violations[0][1] == 4


@pytest.mark.parametrize("body", [
    # narrow type: allowed
    "def f():\n    try:\n        g()\n    except KeyError:\n        pass\n",
    # broad but loud (logs + re-dispatches): allowed
    "def f():\n    try:\n        g()\n    except Exception as e:\n"
    "        log.warning('x %r', e)\n",
])
def test_ignores_acceptable_handlers(tmp_path, body):
    ok = tmp_path / "ok.py"
    ok.write_text(body)
    assert check_excepts.check_file(str(ok)) == []


@pytest.mark.parametrize("call", [
    "jax_solver.solve_cnf_device(clauses, n_vars)",
    "solve_cnf_device(clauses, n_vars)",
    "jax_solver.solve_cnf_device_batch(queries)",
])
def test_detects_dispatch_bypass(tmp_path, call):
    """Rule 2 fires on direct device-solver calls, bare or attribute-form."""
    bad = tmp_path / "bad.py"
    bad.write_text(f"def f(clauses, n_vars, queries):\n    return {call}\n")
    violations = check_excepts.check_device_calls(str(bad))
    assert len(violations) == 1
    assert "dispatch" in violations[0][2]


def test_dispatch_bypass_allows_owning_files(tmp_path):
    """References that are not calls (monkeypatch targets, imports) pass,
    and the two owning files are exempt."""
    ok = tmp_path / "ok.py"
    ok.write_text("from mythril_tpu.parallel.jax_solver import "
                  "solve_cnf_device\nfn = solve_cnf_device\n")
    assert check_excepts.check_device_calls(str(ok)) == []
    for relpath in check_excepts.DEVICE_CALLERS:
        path = os.path.join(check_excepts.REPO_ROOT, relpath)
        assert os.path.exists(path), f"stale DEVICE_CALLERS entry {relpath}"
        assert check_excepts.check_device_calls(path) == []


def test_no_dispatch_bypass_in_tree():
    """The whole package is clean: every device solve goes through
    dispatch.submit()/solve()."""
    violations = [v for v in check_excepts.run() if "bypasses" in v[2]]
    assert not violations, "\n".join(
        f"{path}:{lineno}: {detail}" for path, lineno, detail in violations)
