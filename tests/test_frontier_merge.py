"""On-device state merging at post-dominator join points
(parallel/symstep.py merge_pass + the parallel/frontier.py cadence):

* the synthetic diamond — two fork-sibling lanes reconverged at the
  join collapse to ONE lane whose differing stack slot is an
  ITE(cond, then, else) arena node over the two arm values, the final
  path condition dropped ((P & c) | (P & ~c) = P);
* the soundness gate — arms that diverged in memory must NOT merge
  (mem_sym's byte encoding cannot represent a per-byte ITE);
* the A/B contract — merged and unmerged runs of the same contract
  produce byte-identical detections (fast branchy mini contract, plus
  the full KILLBILLY creation+runtime flow as a slow test), with the
  merged run actually reporting ``frontier.merge.*`` events.
"""

import os
import sys

import numpy as np
import pytest

os.environ.setdefault("MYTHRIL_TPU_LANES", "16")

jax = pytest.importorskip("jax")

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mythril_tpu.parallel import arena as parena
from mythril_tpu.parallel import batch as pbatch
from mythril_tpu.parallel import symstep
from mythril_tpu.smt.solver import sat

pytestmark = pytest.mark.skipif(not sat.have_native(),
                                reason="native CDCL build required")

#: the diamond: JUMPI on a symbolic calldata word forks at pc 5; the
#: taken arm (JUMPDEST@11) pushes 5, the fall-through arm pushes 7,
#: both reach the join JUMPDEST@15 after exactly three steps (the
#: padding JUMPDEST@14 equalizes the arm lengths so the lockstep
#: siblings arrive together) and then spin in the 3-step tail loop
#: 15 -> 16 -> 18 -> 15, staying RUNNING and pc-aligned forever
DIAMOND = bytes.fromhex(
    "6000" "35"          # 0: PUSH1 0; CALLDATALOAD    (symbolic word)
    "600b" "57"          # 3: PUSH1 11; JUMPI          (fork)
    "6007" "600f" "56"   # 6: PUSH1 7; PUSH1 15; JUMP  (fall arm)
    "5b" "6005"          # 11: JUMPDEST; PUSH1 5       (taken arm)
    "5b"                 # 14: JUMPDEST                (padding)
    "5b" "600f" "56")    # 15: JUMPDEST; PUSH1 15; JUMP (join + spin)

#: same diamond, but the fall-through arm also writes memory
#: (MSTORE8 0 <- 7) before the join — both arms push the SAME value 5
#: so the concrete/symbolic stacks agree and only memory diverges
DIAMOND_MEMWRITE = bytes.fromhex(
    "6000" "35"               # 0: PUSH1 0; CALLDATALOAD
    "6010" "57"               # 3: PUSH1 16; JUMPI
    "6005"                    # 6: PUSH1 5            (fall arm, same value)
    "6007" "6000" "53"        # 8: PUSH1 7; PUSH1 0; MSTORE8
    "6017" "56"               # 13: PUSH1 23; JUMP
    "5b" "6005"               # 16: JUMPDEST; PUSH1 5 (taken arm)
    "5b" "5b" "5b" "5b"       # 19: JUMPDEST x4       (length padding)
    "5b" "6017" "56")         # 23: JUMPDEST; PUSH1 23; JUMP (join + spin)

STOP_ONLY = bytes.fromhex("00")


def _diamond_run(code: bytes, n_steps: int):
    """One diamond lane plus one STOP lane (dies immediately, so the
    fork sibling claims it in-step and the two arms run in lockstep)."""
    specs = [pbatch.LaneSpec(code, gas_limit=2 ** 40),
             pbatch.LaneSpec(STOP_ONLY, gas_limit=2 ** 40)]
    state = pbatch.build_batch(specs, stack_slots=16, memory_bytes=128,
                               calldata_bytes=64, retdata_bytes=32,
                               storage_slots=8, tstore_slots=2)
    planes = symstep.SymPlanes.empty(2, 16, 128, 8, max_conds=8)
    arena = parena.new_arena(capacity=1 << 10, const_capacity=1 << 6)
    sched = symstep.new_scheduler(state, planes, 4, 4)
    state, planes, arena, sched = symstep.run_chunk(
        state, planes, arena, sched, n_steps)
    return state, planes, arena


def _const_word(arena, node: int) -> int:
    """Decode a CONST arena node's 256-bit pool word to a Python int."""
    op = int(np.asarray(arena.op)[node])
    assert op == parena.CONST, f"node {node} is op {op:#x}, not CONST"
    limbs = np.asarray(arena.const_vals)[int(np.asarray(arena.imm)[node])]
    return sum(int(limb) << (16 * i) for i, limb in enumerate(limbs))


def test_diamond_siblings_collapse_to_one_lane():
    """After both arms reconverge at the join, one merge pass retires
    the fall-through sibling and rewrites the survivor: path condition
    popped, stack slot 0 ITE-blended from the two arm constants."""
    # chunk length 10: fork at step 4, arms take 3 steps, and the tail
    # loop (period 3) has both lanes sitting exactly ON the join pc 15
    state, planes, arena, = _diamond_run(DIAMOND, n_steps=10)
    st = np.asarray(state.status)
    assert (st == symstep.RUNNING).sum() == 2  # both arms still live
    np.testing.assert_array_equal(np.asarray(state.pc), [15, 15])
    cond_node = int(np.asarray(planes.conds)[0, 0])
    assert cond_node > 0 and int(np.asarray(planes.conds)[1, 0]) \
        == -cond_node  # signed fork siblings

    state, planes, arena, stats = symstep.merge_pass(
        state, planes, arena, np.asarray([15], dtype=np.int32),
        n_rounds=2)
    stats = np.asarray(stats)

    assert int(stats[0]) == 1  # exactly one pair merged
    st = np.asarray(state.status)
    assert (st == symstep.RUNNING).sum() == 1
    assert (st == symstep.DEAD).sum() == 1
    survivor = int(np.argmax(st == symstep.RUNNING))
    # survivor carries the TAKEN side's positive condition... popped:
    # (P & c) | (P & ~c) = P leaves an empty path condition
    assert int(np.asarray(planes.cond_count)[survivor]) == 0
    assert not np.asarray(planes.conds)[survivor].any()
    # stack slot 0 is now ite(cond, 5, 7) through the arena
    ite = int(np.asarray(planes.stack_sym)[survivor, 0])
    assert ite > 0
    assert int(np.asarray(arena.op)[ite]) == 0x0F
    assert int(np.asarray(arena.a)[ite]) == cond_node
    assert _const_word(arena, int(np.asarray(arena.b)[ite])) == 5
    assert _const_word(arena, int(np.asarray(arena.c)[ite])) == 7
    # stats attribution: the merge landed on the tagged join pc, with
    # one blended slot (depth-histogram bucket "1")
    fixed = symstep.MERGE_STATS_FIXED
    assert int(stats[1]) == 1                    # one ITE blend
    assert int(stats[fixed]) == 1                # tag_hits[merge@0xf]
    depth_hist = stats[fixed + 1:]
    assert int(depth_hist[symstep.MERGE_DEPTH_LABELS.index("1")]) == 1


def test_diamond_memory_divergence_blocks_merge():
    """The fall-through arm wrote memory before the join: the byte
    planes cannot express a per-byte ITE, so the pair must NOT merge —
    a missed merge is a perf loss, a wrong one a soundness hole."""
    state, planes, arena = _diamond_run(DIAMOND_MEMWRITE, n_steps=10)
    st = np.asarray(state.status)
    assert (st == symstep.RUNNING).sum() == 2
    assert np.asarray(state.pc)[0] == np.asarray(state.pc)[1]

    state, planes, arena, stats = symstep.merge_pass(
        state, planes, arena, np.asarray([23], dtype=np.int32),
        n_rounds=2)

    assert int(np.asarray(stats)[0]) == 0
    st = np.asarray(state.status)
    assert (st == symstep.RUNNING).sum() == 2  # both arms keep exploring


#: a reconverging diamond ahead of an unprotected SELFDESTRUCT: both
#: arms are 3 steps long (the pad JUMPDEST equalizes them) so the fork
#: siblings arrive at the join in lockstep, then SSTORE the arm value
#: — it stays live (stack, then storage) so whichever boundary the
#: merge pass lands on has at least one differing slot to ITE-blend
BRANCHY = {
    "boom()":
        "PUSH1 0x00\nCALLDATALOAD\nPUSH1 0x01\nAND\n"
        "PUSH @odd\nJUMPI\n"
        "PUSH1 0x07\nPUSH @join\nJUMP\n"
        "odd:\nJUMPDEST\nPUSH1 0x05\nJUMPDEST\n"
        "join:\nJUMPDEST\nPUSH1 0x00\nSSTORE\nJUMPDEST\n"
        "CALLER\nSELFDESTRUCT",
}


def _analyze_branchy(merge_flag: bool, monkeypatch):
    """One BRANCHY device-engine run with the state-merge flag forced
    and a tiny chunk (so chunk boundaries — where the merge pass runs —
    land while the reconverged siblings are still in lockstep)."""
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)
    from mythril_tpu.observe import metrics
    from mythril_tpu.support.support_args import args as support_args

    monkeypatch.setattr(support_args, "state_merge", merge_flag)
    monkeypatch.setenv("MYTHRIL_TPU_CHUNK", "2")
    metrics.reset("frontier.merge")
    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(BRANCHY)))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30, transaction_count=1,
        modules=["AccidentallyKillable"], compulsory_statespace=False,
        engine="tpu")
    issues = fire_lasers(wrapper, white_list=["AccidentallyKillable"])
    detections = sorted(
        (issue.swc_id, issue.address, issue.function,
         [step.get("input") for step in
          issue.transaction_sequence["steps"]])
        for issue in issues)
    return detections, metrics.snapshot()


def test_merge_ab_detections_identical(monkeypatch):
    """The veritesting contract: merging must be invisible to the
    detectors — the same issues with the pass on and off — while the
    merged run actually reports merge events (the frontier trigger,
    the kernel, and the ITE materialization all fired). The witness
    calldata is compared by selector: the merged path's constraint is
    the (weaker) disjunction of the two arms, so the solver may pick a
    different — still valid — concrete model for the unconstrained
    branch word."""
    merged, snap_on = _analyze_branchy(True, monkeypatch)
    unmerged, snap_off = _analyze_branchy(False, monkeypatch)

    def norm(detections):
        return [(swc, addr, fn, [step[:10] for step in steps])
                for swc, addr, fn, steps in detections]

    assert norm(merged) == norm(unmerged)
    assert [d[0] for d in merged] == ["106"]
    assert snap_on.get("frontier.merge.events", 0) >= 1
    assert snap_on.get("frontier.merge.lanes_retired", 0) >= 1
    assert snap_on.get("frontier.merge.ites", 0) >= 1
    assert snap_off.get("frontier.merge.events", 0) == 0


@pytest.mark.slow
def test_merge_ab_killbilly_parity(monkeypatch):
    """Full creation+runtime multi-transaction flow (KILLBILLY) stays
    byte-identical in detections with the merge pass on and off."""
    from test_analysis import KILLBILLY

    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)
    from mythril_tpu.support.support_args import args as support_args

    def run(merge_flag: bool):
        monkeypatch.setattr(support_args, "state_merge", merge_flag)
        reset_callback_modules()
        creation = creation_wrapper(assemble(dispatcher(KILLBILLY)))
        wrapper = SymExecWrapper(
            creation.hex(), address=None, strategy="bfs", max_depth=128,
            execution_timeout=240, create_timeout=30, transaction_count=2,
            modules=["AccidentallyKillable"], compulsory_statespace=False,
            engine="tpu")
        issues = fire_lasers(wrapper, white_list=["AccidentallyKillable"])
        return sorted(
            (issue.swc_id, issue.address, issue.function,
             [step.get("input") for step in
              issue.transaction_sequence["steps"]])
            for issue in issues)

    merged = run(True)
    unmerged = run(False)
    assert merged == unmerged
    assert [d[0] for d in merged] == ["106"]
