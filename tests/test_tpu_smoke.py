"""Real-TPU smoke tier (VERDICT r3 missing #5 / next-round #7).

The main suite pins JAX_PLATFORMS=cpu (tests/conftest.py) so CI never
contends for the chip — which also meant nothing ever PROVED the symbolic
frontier runs on real TPU hardware. These tests close that gap: each spawns
a subprocess WITHOUT the cpu pin, skips cleanly when no TPU is reachable,
and asserts the device actually executed work.

Run explicitly with `pytest -m tpu` (deselected by default via pyproject
addopts, selected in the pre-bench sanity pass).
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_on_tpu(snippet: str, timeout: int = 420) -> dict:
    """Run `snippet` in a fresh interpreter with the TPU platform visible.
    The snippet must print one JSON line. Skips the test when no TPU."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # APPEND to PYTHONPATH: the TPU platform plugin registers via a
    # sitecustomize on the existing path (overwriting it silently demotes
    # the subprocess to CPU)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            env=env, capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        # libtpu hanging on instance-metadata fetch IS "no TPU reachable":
        # the probe runs no repo code, so a hang here says nothing about us
        pytest.skip("TPU platform probe hung (no reachable TPU)")
    if "tpu" not in probe.stdout:
        pytest.skip(f"no TPU platform visible: {probe.stdout!r}")
    result = subprocess.run([sys.executable, "-c", snippet], env=env,
                            capture_output=True, text=True, timeout=timeout)
    assert result.returncode == 0, result.stderr[-2000:]
    return json.loads(result.stdout.strip().splitlines()[-1])


def test_symbolic_frontier_runs_on_tpu():
    """A small branchy contract explored by `--engine tpu` ON THE CHIP:
    device forks must happen and the issue pipeline must stay intact."""
    out = _run_on_tpu("""
import json, os
os.environ["MYTHRIL_TPU_LANES"] = "16"
os.environ["MYTHRIL_TPU_MAX_STEPS"] = "256"
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontends.asm import assemble, creation_wrapper, dispatcher
src = {"probe()": "PUSH1 0x04\\nCALLDATALOAD\\nPUSH1 0x2a\\nLT\\n"
                   "PUSH @a\\nJUMPI\\nSTOP\\na:\\nJUMPDEST\\n"
                   "PUSH1 0x24\\nCALLDATALOAD\\nPUSH1 0x63\\nGT\\n"
                   "PUSH @b\\nJUMPI\\nSTOP\\nb:\\nJUMPDEST\\nSTOP"}
creation = creation_wrapper(assemble(dispatcher(src)))
wrapper = SymExecWrapper(
    creation.hex(), address=None, strategy="bfs", max_depth=128,
    execution_timeout=240, create_timeout=60, transaction_count=1,
    compulsory_statespace=False, run_analysis_modules=False, engine="tpu")
import jax
print(json.dumps({
    "backend": jax.devices()[0].platform,
    "forks": getattr(wrapper.laser, "frontier_forks", 0),
    "lane_steps": getattr(wrapper.laser, "frontier_lane_steps", 0),
}))
""")
    assert out["backend"] == "tpu"
    assert out["forks"] > 0, f"no device forks on real TPU: {out}"
    assert out["lane_steps"] > 0


def test_device_solver_runs_on_tpu():
    """A bit-blasted query solved by the device DPLL lane on the chip."""
    out = _run_on_tpu("""
import json
from mythril_tpu.smt import symbol_factory, UGT, ULT
from mythril_tpu.smt.solver.bitblast import Blaster
from mythril_tpu.parallel import jax_solver
x = symbol_factory.BitVecSym("smoke_x", 32)
from mythril_tpu.smt.solver.preprocess import lower_constraints
lowered, _ = lower_constraints([(UGT(x, 500)).raw, (ULT(x, 503)).raw])
blaster = Blaster()
for c in lowered:
    blaster.assert_true(c)
status, model = jax_solver.solve_cnf_device(
    blaster.clauses, blaster.n_vars, max_steps=20000)
import jax
print(json.dumps({"backend": jax.devices()[0].platform, "status": status}))
""")
    assert out["backend"] == "tpu"
    assert out["status"] == 1, f"device DPLL did not solve on TPU: {out}"
