"""Unit tests for the observe read-side surface ISSUE 12 added:
Prometheus text exposition (observe/export.py), the bounded snapshot
ring, device-memory accounting, and structured logging with correlation
ids (observe/slog.py).

The exposition tests double as the acceptance proof for the scrape
contract: every rendered series resolves to a declared metric and every
``# HELP`` line carries that metric's registry doc verbatim.
"""

import json
import re

import pytest

from mythril_tpu.observe import export, metrics, slog


@pytest.fixture(autouse=True)
def _clean_observability(monkeypatch):
    monkeypatch.delenv("MYTHRIL_TPU_SLOG", raising=False)
    monkeypatch.delenv("MYTHRIL_TPU_METRICS_RING", raising=False)
    metrics.reset()
    slog.reset()
    export.reset_ring()
    yield
    metrics.reset()
    slog.reset()
    export.reset_ring()


# -- Prometheus exposition -----------------------------------------------------------

#: suffixes the renderer may append to a metric's Prometheus name
_SUFFIXES = ("_total", "_sum", "_count", "_reservoir_dropped")


def _base_name(series_line: str) -> str:
    """``mythril_tpu_x_total{a="b"} 3`` -> the declared-metric part."""
    name = re.split(r"[{ ]", series_line, maxsplit=1)[0]
    for suffix in _SUFFIXES:
        if name.endswith(suffix):
            candidate = name[: -len(suffix)]
            if candidate in _DECLARED_PROM:
                return candidate
    return name


_DECLARED_PROM = {export.prometheus_name(name) for name in metrics.REGISTRY}


def test_every_exposition_line_names_a_declared_metric():
    metrics.inc("serve.requests", 3)
    metrics.set_gauge("frontier.telemetry.occupancy", 0.5)
    metrics.observe("dispatch.flush.latency_ms", 12.5)
    metrics.observe("profiler.instruction_us", 7.0, label="ADD")
    text = export.render_prometheus()
    assert text.endswith("\n")
    docs = {export.prometheus_name(spec.name): spec.doc
            for spec in metrics._METRICS}
    for line in text.splitlines():
        assert line, "exposition must not contain blank lines"
        if line.startswith("# HELP "):
            name, doc = line[len("# HELP "):].split(" ", 1)
            assert name in _DECLARED_PROM, f"HELP for undeclared {name}"
            assert doc == docs[name].replace("\n", "\\n"), \
                f"HELP drifted from the registry doc for {name}"
        elif line.startswith("# TYPE "):
            name, kind = line[len("# TYPE "):].split(" ", 1)
            assert name in _DECLARED_PROM
            assert kind in ("counter", "gauge", "summary")
        else:
            assert _base_name(line) in _DECLARED_PROM, \
                f"series line for undeclared metric: {line!r}"
    # the whole declared surface renders, even never-touched metrics
    for prom in _DECLARED_PROM:
        assert f"# HELP {prom} " in text


def test_counter_and_gauge_rendering():
    metrics.inc("serve.requests", 3)
    metrics.set_gauge("frontier.telemetry.arena_bytes", 4096)
    text = export.render_prometheus()
    assert "\nmythril_tpu_serve_requests_total 3\n" in text
    assert "\nmythril_tpu_frontier_telemetry_arena_bytes 4096\n" in text
    # untouched scalars still render as 0
    assert "\nmythril_tpu_serve_busy_rejections_total 0\n" in text


def test_histogram_renders_as_summary_with_quantiles_and_labels():
    for value in (10.0, 20.0, 30.0, 40.0):
        metrics.observe("dispatch.flush.latency_ms", value)
    metrics.observe("profiler.instruction_us", 7.0, label="ADD")
    text = export.render_prometheus()
    prom = "mythril_tpu_dispatch_flush_latency_ms"
    assert f'{prom}{{quantile="0.5"}} 20.0' in text
    assert f'{prom}{{quantile="0.95"}} 40.0' in text
    assert f"{prom}_sum 100.0" in text
    assert f"{prom}_count 4" in text
    # the per-label breakdown rides a label="..." dimension
    assert ('mythril_tpu_profiler_instruction_us'
            '{label="ADD",quantile="0.5"} 7.0') in text
    # unobserved histograms render zero sum/count, no quantile series
    assert "mythril_tpu_serve_request_ms_sum 0.0" in text
    assert "mythril_tpu_serve_request_ms_count 0" in text
    assert "mythril_tpu_serve_request_ms{" not in text


def test_help_lines_escape_newlines_and_backslashes():
    assert export._escape_help("a\nb\\c") == "a\\nb\\\\c"
    assert export._escape_label('say "hi"\n') == 'say \\"hi\\"\\n'


def test_collect_device_memory_never_raises():
    stats = export.collect_device_memory()
    assert isinstance(stats, dict)
    if stats:  # an accelerator with memory_stats() was visible
        assert stats["devices"] >= 1
        assert metrics.value("device.hbm.bytes_in_use") == \
            stats["bytes_in_use"]


# -- snapshot ring -------------------------------------------------------------------


def test_ring_is_bounded_and_sequenced(monkeypatch):
    monkeypatch.setenv("MYTHRIL_TPU_METRICS_RING", "4")
    export.reset_ring()
    ring = export.ring()
    assert ring.capacity == 4
    for i in range(10):
        metrics.inc("serve.requests")
        ring.record(request_id=f"r{i}")
    assert len(ring) == 4
    entries = ring.tail()
    assert [entry["request_id"] for entry in entries] == \
        ["r6", "r7", "r8", "r9"]
    seqs = [entry["seq"] for entry in entries]
    assert seqs == sorted(seqs) and seqs[-1] == 10
    assert entries[-1]["metrics"]["serve.requests"] == 10
    assert ring.tail(2) == entries[-2:]


def test_record_snapshot_uses_the_process_ring():
    entry = export.record_snapshot(scrape="s1")
    assert entry["scrape"] == "s1" and "metrics" in entry
    assert export.ring().tail()[-1]["seq"] == entry["seq"]


# -- structured logging --------------------------------------------------------------


def test_slog_disabled_is_a_noop(tmp_path):
    sink = tmp_path / "never.slog"
    assert not slog.enabled()
    slog.event("frontier.chunk", running=8)  # must not raise or write
    assert not sink.exists()


def test_slog_writes_json_lines_with_correlation_scope(tmp_path):
    sink = str(tmp_path / "run.slog")
    slog.enable(sink)
    assert slog.enabled() and slog.sink_path() == sink
    slog.event("serve.listening", transport="stdio")
    cid = slog.new_correlation_id()
    with slog.correlated(cid) as scoped:
        assert scoped == cid and slog.correlation_id() == cid
        slog.event("frontier.chunk", running=8, stack=3)
    assert slog.correlation_id() is None  # scope restored
    records = [json.loads(line)
               for line in open(sink, encoding="utf-8")]
    assert [record["event"] for record in records] == \
        ["serve.listening", "frontier.chunk"]
    assert records[0]["cid"] is None
    assert records[1]["cid"] == cid
    assert records[1]["running"] == 8 and records[1]["stack"] == 3
    assert all("ts" in record for record in records)


def test_slog_env_knob_enables_at_first_use(tmp_path, monkeypatch):
    sink = str(tmp_path / "env.slog")
    monkeypatch.setenv("MYTHRIL_TPU_SLOG", sink)
    slog.reset()  # back to never-touched: env re-read at next use
    slog.event("dispatch.flush", occupancy=4)
    assert slog.enabled()
    record = json.loads(open(sink, encoding="utf-8").read())
    assert record["event"] == "dispatch.flush"
    assert record["occupancy"] == 4


def test_correlation_ids_are_unique_and_shaped():
    first = slog.new_correlation_id()
    second = slog.new_correlation_id()
    assert first != second
    assert re.fullmatch(r"c[0-9a-f]+-[0-9a-f]{6}-\d+", first)


def test_slog_survives_a_dead_sink(tmp_path):
    sink = str(tmp_path / "dead.slog")
    slog.enable(sink)
    slog._SLOGGER._handle.close()  # simulate the sink dying under us
    slog.event("serve.reply", ok=True)  # must not raise
    assert not slog.enabled()  # logger turned itself off
