"""Failure taxonomy, circuit breaker, degradation ladder, fault injection,
and crash-safe checkpoint/resume (ISSUE 2).

Ladder coverage never runs a real device solve (the jax DPLL pays minutes of
XLA compile per clause shape): `solve_cnf_device` is monkeypatched at the
module attribute, and device failures are produced by the deterministic
fault plan (`--inject-fault CLASS[:NTH]`) firing at the exact boundaries the
production code guards."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from mythril_tpu.smt.solver import sat
from mythril_tpu.smt.solver import solver as solver_module
from mythril_tpu.smt.solver.solver_statistics import SolverStatistics
from mythril_tpu.support import resilience
from mythril_tpu.support.support_args import args

#: (clauses, n_vars, expected) decision fixtures exercised on every rung
SAT_CNF = ([[1, 2], [-1], [2]], 2, sat.SAT)
UNSAT_CNF = ([[1], [-1]], 1, sat.UNSAT)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    from mythril_tpu.smt.solver import dispatch

    resilience.reset()
    SolverStatistics().reset()
    dispatch.reset()  # the batch layer's verdict cache must not leak across tests
    monkeypatch.setattr(args, "device_crosscheck", 0)
    yield
    resilience.reset()
    SolverStatistics().reset()
    dispatch.reset()


# -- taxonomy -------------------------------------------------------------------------


def test_classify_failure_taxonomy():
    assert resilience.classify_failure(resilience.DeviceOOM("x")) == \
        resilience.DEVICE_OOM
    assert resilience.classify_failure(MemoryError()) == resilience.DEVICE_OOM
    assert resilience.classify_failure(
        RuntimeError("RESOURCE_EXHAUSTED: hbm allocator")) == \
        resilience.DEVICE_OOM
    assert resilience.classify_failure(TimeoutError()) == \
        resilience.WALL_OVERRUN

    class UnexpectedTracerError(Exception):
        pass

    assert resilience.classify_failure(UnexpectedTracerError("leak")) == \
        resilience.COMPILE_ERROR
    assert resilience.classify_failure(
        RuntimeError("INVALID_ARGUMENT: bad shape")) == \
        resilience.COMPILE_ERROR
    assert resilience.classify_failure(RuntimeError("boom")) == \
        resilience.WORKER_CRASH


# -- fault plan -----------------------------------------------------------------------


def test_fault_plan_nth_semantics():
    plan = resilience.FaultPlan("device_oom:3")
    assert [plan.visit("device") for _ in range(4)] == \
        [None, None, resilience.DEVICE_OOM, None]

    plan = resilience.FaultPlan("native_crash:2+")
    assert [plan.visit("native") for _ in range(4)] == \
        [None] + [resilience.NATIVE_CRASH] * 3

    plan = resilience.FaultPlan("divergence:*")
    assert all(plan.visit("divergence") == resilience.DIVERGENCE
               for _ in range(3))

    # default NTH is 1; entries are per-site, other sites never fire
    plan = resilience.FaultPlan("device_oom")
    assert plan.visit("native") is None
    assert plan.visit("device") == resilience.DEVICE_OOM


def test_fault_plan_rejects_unknown_class():
    with pytest.raises(ValueError):
        resilience.FaultPlan("segfault:1")


def test_fire_raises_typed_exception():
    resilience.configure("compile_error:1")
    with pytest.raises(resilience.DeviceCompileError):
        resilience.fire("device")
    resilience.fire("device")  # visit 2: disarmed


# -- circuit breaker ------------------------------------------------------------------


def test_breaker_trips_recovers_and_counts():
    health = resilience.BackendHealth("device", trip_after=3,
                                      recovery_after=4)
    stats = SolverStatistics()
    for _ in range(2):
        health.record_failure(resilience.DEVICE_OOM, "e")
    assert health.state == resilience.CLOSED
    health.record_failure(resilience.DEVICE_OOM, "e")
    assert health.state == resilience.OPEN
    assert stats.breaker_trips == 1
    assert stats.failure_counts == {"device:device_oom": 3}

    # OPEN skips queries until the recovery window elapses, then lets one
    # half-open probe through
    assert [health.allow() for _ in range(4)] == [False, False, False, True]
    health.record_success()
    assert health.state == resilience.CLOSED
    assert stats.breaker_recoveries == 1

    # a success resets the consecutive-failure count
    health.record_failure(resilience.DEVICE_OOM, "e")
    health.record_success()
    for _ in range(2):
        health.record_failure(resilience.DEVICE_OOM, "e")
    assert health.state == resilience.CLOSED


def test_failed_recovery_probe_rearms_skip_window():
    health = resilience.BackendHealth("device", trip_after=1,
                                      recovery_after=3)
    health.record_failure(resilience.WORKER_CRASH, "e")
    assert health.state == resilience.OPEN
    assert [health.allow() for _ in range(3)] == [False, False, True]
    health.record_failure(resilience.WORKER_CRASH, "probe failed")
    assert health.state == resilience.OPEN
    # the window restarts: two skips again before the next probe
    assert [health.allow() for _ in range(3)] == [False, False, True]


def test_divergence_quarantines_permanently():
    health = resilience.BackendHealth("device", trip_after=3)
    health.record_failure(resilience.DIVERGENCE, "wrong verdict")
    assert health.state == resilience.QUARANTINED
    assert not health.allow()
    health.record_success()  # no resurrection path
    assert health.state == resilience.QUARANTINED
    assert SolverStatistics().backends_quarantined == ["device"]


# -- degradation ladder: identical verdicts on every rung -----------------------------


def test_python_floor_verdicts():
    for clauses, n_vars, expected in (SAT_CNF, UNSAT_CNF):
        status, model = sat.solve_cnf_python(clauses, n_vars)
        assert status == expected
        if status == sat.SAT:
            assert all(any((lit > 0) == model[abs(lit) - 1] for lit in cl)
                       for cl in clauses)


@pytest.mark.skipif(not sat.have_native(),
                    reason="native CDCL build required")
def test_native_rung_matches_python_floor():
    for clauses, n_vars, expected in (SAT_CNF, UNSAT_CNF):
        assert sat.solve_cnf_native(clauses, n_vars)[0] == expected


def test_native_failure_degrades_to_python_same_verdict():
    """native_crash injection at the native boundary: solve_cnf still
    returns the correct verdict (python floor), the failure is classified,
    and the breaker trips after trip_after consecutive failures."""
    resilience.configure("native_crash:*")
    for clauses, n_vars, expected in (SAT_CNF, UNSAT_CNF, SAT_CNF):
        assert sat.solve_cnf(clauses, n_vars)[0] == expected
    stats = SolverStatistics()
    if sat.have_native():
        # 3 consecutive native failures == DEFAULT_TRIP_AFTER: breaker OPEN
        assert stats.failure_counts["native:native_crash"] == 3
        assert resilience.registry.backend(resilience.NATIVE).state == \
            resilience.OPEN
        # while OPEN the native boundary is not even visited: the plan's
        # site counter stays put and verdicts keep coming from the floor
        visits = resilience.plan().site_counts.get("native")
        assert sat.solve_cnf(*SAT_CNF[:2])[0] == sat.SAT
        assert resilience.plan().site_counts.get("native") == visits


def test_device_rung_matches_host_verdicts(monkeypatch):
    """A healthy (simulated) device yields the same verdicts as the host
    rungs. The device function is monkeypatched to decide with the python
    solver — never a real device solve in tier-1."""
    from mythril_tpu.parallel import jax_solver

    monkeypatch.setattr(
        jax_solver, "solve_cnf_device",
        lambda clauses, n_vars, **kw: sat.solve_cnf_python(clauses, n_vars))
    for clauses, n_vars, expected in (SAT_CNF, UNSAT_CNF):
        assert solver_module._device_solve(clauses, n_vars, 10_000)[0] == \
            expected
    stats = SolverStatistics()
    assert stats.device_solved == 2
    assert stats.failure_counts == {}


def test_device_failure_classified_then_breaker_skips(monkeypatch):
    calls = []

    def exploding_device(clauses, n_vars, **kw):
        calls.append(1)
        raise MemoryError("hbm oom")

    from mythril_tpu.parallel import jax_solver

    monkeypatch.setattr(jax_solver, "solve_cnf_device", exploding_device)
    stats = SolverStatistics()
    for _ in range(resilience.DEFAULT_TRIP_AFTER):
        status, _ = solver_module._device_solve(*SAT_CNF[:2], 10_000)
        assert status == sat.UNKNOWN  # caller falls back to the host ladder
    assert stats.failure_counts == {
        "device:device_oom": resilience.DEFAULT_TRIP_AFTER}
    assert resilience.registry.backend(resilience.DEVICE).state == \
        resilience.OPEN
    # breaker OPEN: the device function is no longer even called
    before = len(calls)
    assert solver_module._device_solve(*SAT_CNF[:2], 10_000)[0] == \
        sat.UNKNOWN
    assert len(calls) == before
    assert stats.device_skipped == 1


def test_device_divergence_quarantined_host_verdict_wins(monkeypatch):
    """Injected divergence flips the device verdict; the sampled cross-check
    disproves it against the host oracle, quarantines the backend for the
    run, and returns the HOST verdict."""
    from mythril_tpu.parallel import jax_solver

    monkeypatch.setattr(
        jax_solver, "solve_cnf_device",
        lambda clauses, n_vars, **kw: sat.solve_cnf_python(clauses, n_vars))
    resilience.configure("divergence:1")
    clauses, n_vars, _ = SAT_CNF
    status, model = solver_module._device_solve(clauses, n_vars, 10_000)
    assert status == sat.SAT  # the host oracle's answer, not the flipped one
    assert model is not None
    stats = SolverStatistics()
    assert stats.divergences == 1
    assert stats.backends_quarantined == ["device"]
    assert resilience.registry.backend(resilience.DEVICE).state == \
        resilience.QUARANTINED
    # quarantine is permanent for the run: next query is skipped outright
    assert solver_module._device_solve(clauses, n_vars, 10_000)[0] == \
        sat.UNKNOWN
    assert stats.device_skipped == 1


def test_sampled_crosscheck_passes_healthy_device(monkeypatch):
    from mythril_tpu.parallel import jax_solver

    monkeypatch.setattr(
        jax_solver, "solve_cnf_device",
        lambda clauses, n_vars, **kw: sat.solve_cnf_python(clauses, n_vars))
    monkeypatch.setattr(args, "device_crosscheck", 1)
    for clauses, n_vars, expected in (SAT_CNF, UNSAT_CNF):
        assert solver_module._device_solve(clauses, n_vars, 10_000)[0] == \
            expected
    stats = SolverStatistics()
    assert stats.crosschecks == 2
    assert stats.divergences == 0
    assert resilience.registry.backend(resilience.DEVICE).state == \
        resilience.CLOSED


# -- checkpoint payload validation (satellite) ----------------------------------------


def test_load_checkpoint_rejects_missing_keys(tmp_path):
    import pickle

    from mythril_tpu.support import checkpoint as cp

    path = tmp_path / "truncated.ckpt"
    with open(path, "wb") as handle:
        pickle.dump({"version": cp.FORMAT_VERSION, "tx_index": 1}, handle)
    assert cp.load_host_checkpoint(str(path)) is None

    with open(path, "wb") as handle:
        pickle.dump(["not", "a", "dict"], handle)
    assert cp.load_host_checkpoint(str(path)) is None

    with open(path, "wb") as handle:
        pickle.dump({"version": cp.FORMAT_VERSION + 1}, handle)
    assert cp.load_host_checkpoint(str(path)) is None


def test_fsync_replace_promotes_atomically(tmp_path):
    from mythril_tpu.support.checkpoint import fsync_replace

    target = tmp_path / "ckpt.bin"
    target.write_bytes(b"old")
    tmp = tmp_path / "ckpt.bin.tmp"
    tmp.write_bytes(b"new")
    fsync_replace(str(tmp), str(target))
    assert target.read_bytes() == b"new"
    assert not tmp.exists()


# -- acceptance: analysis-level ladder + kill/resume ----------------------------------

pytestmark_e2e = pytest.mark.skipif(not sat.have_native(),
                                    reason="native CDCL build required")


def _analyze(tx_count, modules, checkpoint=None, resume=None,
             tx_strategy=None):
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)
    from test_analysis import KILLBILLY

    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(KILLBILLY)))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30,
        transaction_count=tx_count, modules=modules,
        compulsory_statespace=False, checkpoint_path=checkpoint,
        resume_path=resume)
    return fire_lasers(wrapper, white_list=modules)


@pytestmark_e2e
def test_inject_device_oom_analysis_completes_via_host_ladder(monkeypatch):
    """ISSUE 2 acceptance: with --inject-fault device_oom:1 on the jax
    solver lane, the analysis completes with the correct issues through the
    host ladder and SolverStatistics records exactly one classified failure
    with the breaker still CLOSED (1 < trip_after)."""
    from mythril_tpu.parallel import jax_solver

    # after the injected failure, the remaining device queries answer
    # UNKNOWN (oversize-style fallback) — never a real device solve, on
    # either the single-query or the batched dispatch route
    monkeypatch.setattr(jax_solver, "solve_cnf_device",
                        lambda clauses, n_vars, **kw: (jax_solver.UNKNOWN,
                                                       None))
    monkeypatch.setattr(jax_solver, "solve_cnf_device_batch",
                        lambda queries, **kw: [(jax_solver.UNKNOWN, None)
                                               for _ in queries])
    modules = ["AccidentallyKillable"]
    baseline = _analyze(2, modules)
    assert sorted(i.swc_id for i in baseline) == ["106"]

    SolverStatistics().reset()
    resilience.reset()
    monkeypatch.setattr(args, "solver", "jax")
    resilience.configure("device_oom:1")
    injected = _analyze(2, modules)
    assert sorted(i.swc_id for i in injected) == ["106"]
    # both witnesses must target the same function: compare the 4-byte
    # selector, not the full calldata — the trailing argument bytes are
    # free in the model (any padding satisfies the query), so their
    # exact concretisation is CDCL-choice-dependent, not semantic
    assert injected[0].transaction_sequence["steps"][-1]["input"][:10] == \
        baseline[0].transaction_sequence["steps"][-1]["input"][:10]

    stats = SolverStatistics()
    assert stats.failure_counts == {"device:device_oom": 1}
    assert resilience.registry.backend(resilience.DEVICE).state == \
        resilience.CLOSED
    assert stats.breaker_trips == 0


@pytestmark_e2e
def test_killed_run_resumes_from_atomic_checkpoint(monkeypatch, tmp_path):
    """ISSUE 2 acceptance: a run killed mid-transaction (host_crash
    injection — the deterministic kill -9) resumes from its last atomic
    checkpoint to the same issue set as an uninterrupted run."""
    modules = ["AccidentallyKillable"]
    full = _analyze(2, modules)
    assert sorted(i.swc_id for i in full) == ["106"]

    # checkpoint every 5 popped states, die at the 13th: the 10-state
    # checkpoint is on disk when the "kill" lands mid-worklist
    monkeypatch.setenv("MYTHRIL_TPU_CHECKPOINT_STATES", "5")
    ckpt = str(tmp_path / "killed.ckpt")
    resilience.configure("host_crash:13")
    with pytest.raises(resilience.InjectedCrash):
        _analyze(2, modules, checkpoint=ckpt)
    assert os.path.exists(ckpt)
    assert not os.path.exists(ckpt + ".tmp")  # atomic: no torn temp file

    resilience.configure(None)  # the resumed process has no fault plan
    resumed = _analyze(2, modules, resume=ckpt)
    assert sorted(i.swc_id for i in resumed) == ["106"]
    assert resumed[0].transaction_sequence["steps"][-1]["input"] == \
        full[0].transaction_sequence["steps"][-1]["input"]
