"""Symbolic-summary plugin tests (capability parity: reference
tests/integration_tests/summary_test.py — findings unchanged with
--enable-summaries on a multi-transaction contract)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from mythril_tpu.smt.solver import sat
from mythril_tpu.support.support_args import args

pytestmark = pytest.mark.skipif(not sat.have_native(),
                                reason="native CDCL build required")


def _analyze_with_summaries(contract, modules, tx_count):
    from test_analysis import analyze

    args.enable_summaries = True
    try:
        return analyze(contract, modules=modules, tx_count=tx_count)
    finally:
        args.enable_summaries = False
        args.use_issue_annotations = False


def test_killbilly_findings_unchanged():
    """The 2-tx selfdestruct chain must survive summary replay: tx1 records
    the activation summary, tx2's kill validates against it."""
    from test_analysis import analyze, KILLBILLY

    baseline = analyze(KILLBILLY, modules=["AccidentallyKillable"], tx_count=2)
    summarized = _analyze_with_summaries(
        KILLBILLY, modules=["AccidentallyKillable"], tx_count=2)
    assert sorted(i.swc_id for i in summarized) == sorted(
        i.swc_id for i in baseline) == ["106"]


def test_safe_contract_still_clean():
    from test_analysis import SAFE_KILL

    summarized = _analyze_with_summaries(
        SAFE_KILL, modules=["AccidentallyKillable"], tx_count=2)
    assert summarized == []


def test_summaries_are_recorded():
    from mythril_tpu.core.plugin.plugins.summary import SymbolicSummaryPlugin
    from mythril_tpu.core.plugin import LaserPluginLoader
    from test_analysis import analyze, KILLBILLY

    args.enable_summaries = True
    try:
        analyze(KILLBILLY, modules=["AccidentallyKillable"], tx_count=2)
        plugin = LaserPluginLoader().plugin_list.get("symbolic-summaries")
        assert plugin is not None
        assert isinstance(plugin, SymbolicSummaryPlugin)
        # the activation tx mutates storage -> at least one recorded summary
        assert len(plugin.summaries) >= 1
        assert all(s.as_dict for s in plugin.summaries)
    finally:
        args.enable_summaries = False
        args.use_issue_annotations = False
