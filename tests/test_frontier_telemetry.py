"""Device-resident frontier telemetry plane (parallel/symstep.py):

* decode parity — the on-device opcode-class histogram and lifecycle
  totals must equal a host replay of the same concrete bytecode through
  the SAME classification table (``symstep.OP_CLASS``);
* tag occupancy — lanes sitting at an annotated merge/loop pc are
  counted per chunk;
* the telemetry-off null — compiling the plane out must not change the
  number of host syncs (``jax.device_get`` calls) or the detections;
* the overhead budget (slow) — stress-bench device step rate with
  telemetry on stays within 5% of telemetry-off.
"""

import os
import sys

import numpy as np
import pytest

os.environ.setdefault("MYTHRIL_TPU_LANES", "16")

jax = pytest.importorskip("jax")

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mythril_tpu.parallel import arena as parena
from mythril_tpu.parallel import batch as pbatch
from mythril_tpu.parallel import symstep
from mythril_tpu.smt.solver import sat

pytestmark = pytest.mark.skipif(not sat.have_native(),
                                reason="native CDCL build required")

#: straight-line concrete body: PUSH1 5; PUSH1 10; ADD; PUSH1 0; MSTORE;
#: PUSH1 3; PUSH1 7; LT; POP; PUSH1 1; DUP1; SWAP1; POP; POP; STOP —
#: no jumps, so the host replay is a static walk of the byte stream
STRAIGHT_LINE = bytes.fromhex(
    "6005" "600a" "01" "6000" "52"
    "6003" "6007" "10" "50"
    "6001" "80" "90" "50" "50" "00")


def host_replay_histogram(code: bytes) -> np.ndarray:
    """Walk a jump-free byte stream exactly as one device lane executes
    it, counting per opcode class via the shared symstep.OP_CLASS table.
    The halting op (STOP here) is a counted step: the lane is RUNNING
    when it executes it."""
    hist = np.zeros(symstep.N_OP_CLASSES, dtype=np.int64)
    pc = 0
    while pc < len(code):
        op = code[pc]
        hist[symstep.OP_CLASS[op]] += 1
        if op == 0x00:  # STOP — lane leaves RUNNING after this step
            break
        pc += 1 + (op - 0x5F if 0x60 <= op <= 0x7F else 0)
    return hist


def _device_run(code: bytes, n_lanes: int, n_steps: int, tag_pcs=None):
    """run_chunk with a telemetry-armed scheduler; returns the final
    scheduler (telemetry words still on device until np.asarray)."""
    specs = [pbatch.LaneSpec(code, gas_limit=2 ** 40)
             for _ in range(n_lanes)]
    state = pbatch.build_batch(specs, stack_slots=16, memory_bytes=128,
                               calldata_bytes=64, retdata_bytes=32,
                               storage_slots=8, tstore_slots=2)
    planes = symstep.SymPlanes.empty(n_lanes, 16, 128, 8, max_conds=8)
    arena = parena.new_arena(capacity=1 << 10, const_capacity=1 << 6)
    telemetry = symstep.new_telemetry(tag_pcs or [])
    sched = symstep.new_scheduler(state, planes, 2 * n_lanes, 2 * n_lanes,
                                  telemetry=telemetry)
    state, planes, arena, sched = symstep.run_chunk(
        state, planes, arena, sched, n_steps)
    return sched


def _decode(sched):
    """Slice the packed telemetry words exactly as frontier's decode
    does (op_hist | lifecycle | esc_cause | occupancy | hwm | tag_occ)."""
    words = np.asarray(symstep.telemetry_words(sched.telemetry),
                       dtype=np.int64)
    n_op, n_lc = symstep.N_OP_CLASSES, symstep.N_LIFECYCLE
    n_ec = symstep.N_ESC_CAUSES
    return {
        "op_hist": words[:n_op],
        "lifecycle": dict(zip(symstep.LIFECYCLE_NAMES,
                              words[n_op:n_op + n_lc])),
        "esc_cause": dict(zip(symstep.ESC_CAUSE_NAMES,
                              words[n_op + n_lc:n_op + n_lc + n_ec])),
        "occupancy": words[n_op + n_lc + n_ec:n_op + n_lc + n_ec + 2],
        "hwm": words[n_op + n_lc + n_ec + 2:symstep.TELEMETRY_FIXED_WORDS],
        "tag_occ": words[symstep.TELEMETRY_FIXED_WORDS:],
    }


def test_opcode_histogram_matches_host_replay():
    """Every lane executes the identical straight-line sequence, so the
    device histogram must be the host replay times the lane count — and
    the lifecycle totals must show every lane escaping at the STOP."""
    n_lanes = 8
    expected = host_replay_histogram(STRAIGHT_LINE)
    sched = _device_run(STRAIGHT_LINE, n_lanes, n_steps=32)
    tel = _decode(sched)

    np.testing.assert_array_equal(tel["op_hist"], expected * n_lanes)
    # executed total parity with the scheduler's own exact counter
    assert tel["op_hist"].sum() == int(sched.executed) \
        == expected.sum() * n_lanes
    # all lanes halted at the STOP: escaped (cause: halt), none died
    assert tel["esc_cause"]["halt"] == n_lanes
    assert tel["lifecycle"]["esc_buffered"] \
        + tel["lifecycle"]["esc_frozen"] == n_lanes
    assert tel["lifecycle"]["err_deaths"] == 0
    assert tel["lifecycle"]["overflow_kills"] == 0
    # occupancy: lane-step sum / step count = mean running lanes;
    # the run is front-loaded (all lanes live for len(sequence) steps)
    lane_steps, steps = tel["occupancy"]
    assert steps == 32
    assert lane_steps == expected.sum() * n_lanes


def test_tag_occupancy_counts_lanes_at_annotated_pcs():
    """Lanes at a tagged merge/loop pc are counted each step they sit
    there. Tag pc 2 is the PUSH1 10 at offset 2 of the straight line —
    every lane passes it exactly once."""
    n_lanes = 4
    sched = _device_run(STRAIGHT_LINE, n_lanes, n_steps=32,
                        tag_pcs=[2, 0x7F])  # second tag never reached
    tel = _decode(sched)
    np.testing.assert_array_equal(tel["tag_occ"], [n_lanes, 0])


def test_telemetry_off_scheduler_has_no_plane():
    """telemetry=None compiles the counters out entirely: the default
    scheduler carries no telemetry pytree and run_chunk leaves it None
    (the static-gating contract — off is a different jit program, not a
    masked one)."""
    specs = [pbatch.LaneSpec(STRAIGHT_LINE, gas_limit=2 ** 40)
             for _ in range(4)]
    state = pbatch.build_batch(specs, stack_slots=16, memory_bytes=128,
                               calldata_bytes=64, retdata_bytes=32,
                               storage_slots=8, tstore_slots=2)
    planes = symstep.SymPlanes.empty(4, 16, 128, 8, max_conds=8)
    arena = parena.new_arena(capacity=1 << 10, const_capacity=1 << 6)
    sched = symstep.new_scheduler(state, planes, 8, 8)
    assert sched.telemetry is None
    *_, sched = symstep.run_chunk(state, planes, arena, sched, 4)
    assert sched.telemetry is None


def _analyze_killbilly(engine_flag: bool, monkeypatch):
    """One KILLBILLY device-engine run with the telemetry flag forced,
    counting every jax.device_get host sync. Returns (sync_count,
    canonical detection list)."""
    from test_analysis import KILLBILLY

    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)
    from mythril_tpu.support.support_args import args as support_args

    monkeypatch.setattr(support_args, "frontier_telemetry", engine_flag)
    syncs = [0]
    real_device_get = jax.device_get

    def counting_device_get(x):
        syncs[0] += 1
        return real_device_get(x)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(KILLBILLY)))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30, transaction_count=2,
        modules=["AccidentallyKillable"], compulsory_statespace=False,
        engine="tpu")
    issues = fire_lasers(wrapper, white_list=["AccidentallyKillable"])
    detections = sorted(
        (issue.swc_id, issue.address, issue.function,
         [step.get("input") for step in
          issue.transaction_sequence["steps"]])
        for issue in issues)
    return syncs[0], detections


def test_telemetry_off_null(monkeypatch):
    """The A/B contract: telemetry rides the existing per-chunk summary
    download, so turning it off changes NEITHER the host-sync count nor
    the detections — byte-identical issues either way."""
    syncs_on, detections_on = _analyze_killbilly(True, monkeypatch)
    syncs_off, detections_off = _analyze_killbilly(False, monkeypatch)
    assert detections_on == detections_off
    assert [d[0] for d in detections_on] == ["106"]
    assert syncs_on == syncs_off


@pytest.mark.slow
def test_telemetry_overhead_within_budget():
    """Acceptance: stress-bench device step rate with telemetry on
    within 5% of telemetry-off. Uses the fused-chunk stress shape
    directly (forky dispatcher code, big lane count) so the measurement
    is the device step loop, not host services."""
    import time

    import __graft_entry__ as graft

    n_lanes = 512
    chunk = 256

    def rate(with_telemetry: bool) -> float:
        state, planes = graft._symbolic_batch(n_lanes)
        arena = parena.new_arena(capacity=1 << 14, const_capacity=1 << 8)
        telemetry = symstep.new_telemetry([2, 9]) if with_telemetry \
            else None
        sched = symstep.new_scheduler(state, planes, 4 * n_lanes,
                                      4 * n_lanes, telemetry=telemetry)
        # compile outside the measured window
        out = symstep.run_chunk(state, planes, arena, sched, chunk)
        jax.block_until_ready(out[0].status)
        best = 0.0
        for _ in range(3):
            start = time.perf_counter()
            out = symstep.run_chunk(state, planes, arena, sched, chunk)
            jax.block_until_ready(out[0].status)
            best = max(best, chunk * n_lanes
                       / (time.perf_counter() - start))
        return best

    rate_off = rate(False)
    rate_on = rate(True)
    assert rate_on >= 0.95 * rate_off, (
        f"telemetry overhead over budget: {rate_on:.0f} vs "
        f"{rate_off:.0f} lane-steps/s ({rate_on / rate_off:.1%})")
