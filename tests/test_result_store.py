"""Content-addressed result store tests: keying, persistence,
two-daemon union-merge, corrupt-sidecar tolerance, eviction, and the
quarantine interaction. Stdlib-only — no engine, no jax."""

import json
import threading

import pytest

from mythril_tpu.observe import metrics
from mythril_tpu.serve.quarantine import QuarantineStore, contract_key
from mythril_tpu.serve.result_store import (
    RESULTS_VERSION, ResultStore, load_results, result_key,
    results_path_for, save_results)


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _payload(issues=0):
    return {"issue_count": issues, "incomplete": False, "coverage": {},
            "report": {"issues": []}}


# -- keying --------------------------------------------------------------------------


def test_result_key_is_config_sensitive():
    base = {"code": "6001"}
    key = result_key(base)
    # identical request → identical key (content addressing)
    assert result_key({"code": "6001"}) == key
    # bytecode normalization: 0x prefix and case do not split the key
    assert result_key({"code": "0x6001"}) == key
    # every config axis must miss — a config change may change the
    # verdict, so it must never serve the old one
    assert result_key({"code": "6001", "transaction_count": 3}) != key
    assert result_key({"code": "6001", "max_depth": 9}) != key
    assert result_key({"code": "6001", "strategy": "dfs"}) != key
    assert result_key({"code": "6001", "solver": "brute"}) != key
    assert result_key({"code": "6001", "engine": "tpu"}) != key
    assert result_key({"code": "6001", "bin_runtime": True}) != key
    assert result_key({"code": "6001", "modules": ["Suicide"]}) != key


def test_result_key_discriminates_op():
    # an analyze verdict and an optimize report for the same bytecode
    # are different results: the op is key material, never a collision
    base = {"code": "6001"}
    analyze = result_key(base, op="analyze")
    optimize = result_key(base, op="optimize")
    assert analyze != optimize
    # the default op is analyze (pre-optimize sidecars keep hitting)
    assert result_key(base) == analyze
    # op discrimination composes with the config axes
    assert result_key(base, solver="brute", op="optimize") != optimize


def test_analyze_then_optimize_same_bytecode_never_collide(tmp_path):
    # the PR-20 sequence: a daemon analyzes a contract, then gets an
    # optimize request for the SAME bytecode — the cached analyze
    # verdict must not answer it, and vice versa
    store = ResultStore(path=str(tmp_path / "warmset.results.json"))
    params = {"code": "0x600260020200"}
    analyze_key = result_key(params, op="analyze")
    assert store.put(analyze_key, _payload(issues=1))
    assert store.get(result_key(params, op="optimize")) is None
    optimize_payload = {"incomplete": False, "code_out": "600400fefefe",
                        "gas_saved": 8, "rewrites": []}
    assert store.put(result_key(params, op="optimize"), optimize_payload)
    assert store.get(analyze_key)["issue_count"] == 1
    assert store.get(result_key(params, op="optimize"))["gas_saved"] == 8


def test_result_key_applies_daemon_defaults():
    # an explicit "solver": "cdcl" and an omitted solver under a cdcl
    # daemon are the same effective config → the same key
    assert result_key({"code": "60", "solver": "cdcl"}, solver="cdcl") == \
        result_key({"code": "60"}, solver="cdcl")
    assert result_key({"code": "60"}, solver="cdcl") != \
        result_key({"code": "60"}, solver="brute")


def test_result_key_ignores_scheduling_fields():
    # deadline/priority shape scheduling, not the analysis result
    assert result_key({"code": "60", "deadline_ms": 50,
                       "priority": "bulk"}) == result_key({"code": "60"})


def test_results_path_sits_beside_manifest():
    assert results_path_for("/tmp/x/warmset.json") == \
        "/tmp/x/warmset.results.json"


# -- store basics --------------------------------------------------------------------


def test_put_get_roundtrip_and_persistence(tmp_path):
    sidecar = str(tmp_path / "warmset.results.json")
    store = ResultStore(path=sidecar)
    key = result_key({"code": "6001"})
    assert store.get(key) is None  # miss on cold store
    assert store.put(key, _payload(issues=2))
    hit = store.get(key)
    assert hit["issue_count"] == 2
    # a mutation of the returned payload must not poison the store
    hit["issue_count"] = 99
    assert store.get(key)["issue_count"] == 2
    # a second daemon loading the same sidecar sees the entry
    reborn = ResultStore(path=sidecar)
    assert reborn.get(key)["issue_count"] == 2
    assert metrics.value("cache.result.stored") == 1
    assert metrics.value("cache.result.hits") == 3
    assert metrics.value("cache.result.misses") == 1


def test_put_refuses_incomplete_payloads(tmp_path):
    store = ResultStore(path=str(tmp_path / "r.results.json"))
    key = result_key({"code": "60"})
    partial = _payload()
    partial["incomplete"] = True
    assert not store.put(key, partial)
    assert store.get(key) is None


def test_put_strips_cached_marker(tmp_path):
    store = ResultStore(path=str(tmp_path / "r.results.json"))
    key = result_key({"code": "60"})
    marked = _payload()
    marked["cached"] = True  # a replayed cached reply must not nest
    assert store.put(key, marked)
    assert "cached" not in store.get(key)


def test_config_mismatch_misses(tmp_path):
    store = ResultStore(path=str(tmp_path / "r.results.json"))
    assert store.put(result_key({"code": "6001"}), _payload())
    # same bytecode, different analysis config → different key → miss
    assert store.get(result_key({"code": "6001",
                                 "transaction_count": 4})) is None
    assert store.status()["hit_rate"] == 0.0


# -- two-daemon union-merge ----------------------------------------------------------


def test_concurrent_daemons_union_merge(tmp_path):
    sidecar = str(tmp_path / "shared.results.json")
    a = ResultStore(path=sidecar)
    b = ResultStore(path=sidecar)
    key_a = result_key({"code": "6001"})
    key_b = result_key({"code": "6002"})
    assert a.put(key_a, _payload(issues=1))
    assert b.put(key_b, _payload(issues=2))
    # both writes survive on disk: union, not clobber
    merged = load_results(sidecar)
    assert set(merged) == {key_a, key_b}
    reborn = ResultStore(path=sidecar)
    assert reborn.get(key_a)["issue_count"] == 1
    assert reborn.get(key_b)["issue_count"] == 2


def test_union_merge_under_thread_contention(tmp_path):
    sidecar = str(tmp_path / "contended.results.json")
    stores = [ResultStore(path=sidecar) for _ in range(4)]
    keys = [result_key({"code": f"60{i:02x}"}) for i in range(12)]

    def hammer(store, offset):
        for i, key in enumerate(keys):
            store.put(key, _payload(issues=offset * 100 + i))

    threads = [threading.Thread(target=hammer, args=(store, n))
               for n, store in enumerate(stores)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert set(load_results(sidecar)) == set(keys)  # nothing lost


def test_collision_resolves_by_higher_seq(tmp_path):
    sidecar = str(tmp_path / "c.results.json")
    key = result_key({"code": "60"})
    save_results(sidecar, {key: {"seq": 5, "payload": _payload(issues=5)}})
    # a lower-seq write for the same key loses…
    save_results(sidecar, {key: {"seq": 3, "payload": _payload(issues=3)}})
    assert load_results(sidecar)[key]["payload"]["issue_count"] == 5
    # …a higher-seq write wins
    save_results(sidecar, {key: {"seq": 9, "payload": _payload(issues=9)}})
    assert load_results(sidecar)[key]["payload"]["issue_count"] == 9


# -- corrupt-sidecar tolerance -------------------------------------------------------


def test_corrupt_sidecar_degrades_to_cold_store(tmp_path):
    sidecar = tmp_path / "bad.results.json"
    sidecar.write_text("{ not json", encoding="utf-8")
    assert load_results(str(sidecar)) == {}
    store = ResultStore(path=str(sidecar))  # must not raise
    key = result_key({"code": "60"})
    assert store.get(key) is None
    assert store.put(key, _payload())  # and recovers by rewriting
    assert load_results(str(sidecar))[key]["payload"]["issue_count"] == 0


def test_unknown_version_and_malformed_entries_skipped(tmp_path):
    future = tmp_path / "future.results.json"
    future.write_text(json.dumps({"version": RESULTS_VERSION + 1,
                                  "results": {"k": {"seq": 1,
                                                    "payload": {}}}}),
                      encoding="utf-8")
    assert load_results(str(future)) == {}
    mixed = tmp_path / "mixed.results.json"
    good = result_key({"code": "60"})
    mixed.write_text(json.dumps({
        "version": RESULTS_VERSION,
        "results": {
            good: {"seq": 2, "payload": _payload(issues=7)},
            "no-payload": {"seq": 1},
            "not-a-dict": "nope",
        }}), encoding="utf-8")
    loaded = load_results(str(mixed))
    assert set(loaded) == {good}
    assert loaded[good]["payload"]["issue_count"] == 7


# -- eviction ------------------------------------------------------------------------


def test_eviction_beyond_max_drops_oldest(tmp_path):
    sidecar = str(tmp_path / "cap.results.json")
    store = ResultStore(path=sidecar, max_entries=3)
    keys = [result_key({"code": f"60{i:02x}"}) for i in range(5)]
    for i, key in enumerate(keys):
        assert store.put(key, _payload(issues=i))
    # oldest two evicted, newest three retained — in memory and on disk
    assert store.get(keys[0]) is None and store.get(keys[1]) is None
    assert all(store.get(k) is not None for k in keys[2:])
    disk = load_results(sidecar)
    assert set(disk) == set(keys[2:])
    assert metrics.value("cache.result.evicted") >= 2
    assert store.status()["entries"] == 3


# -- quarantine interaction ----------------------------------------------------------


def test_quarantined_hash_never_cached_never_served(tmp_path):
    quarantine = QuarantineStore(threshold=1)
    store = ResultStore(path=str(tmp_path / "q.results.json"),
                        quarantine=quarantine)
    chash = contract_key("6001")
    key = result_key({"code": "6001"})
    # cached before quarantine: the crash must invalidate the answer
    assert store.put(key, _payload(), contract_hash=chash)
    quarantine.record_crash(chash, "worker_segv")
    assert quarantine.is_quarantined(chash)
    assert store.get(key, contract_hash=chash) is None
    # and a poisoned hash can never (re-)enter the cache
    assert not store.put(key, _payload(), contract_hash=chash)
