"""R8 bad fixture: hooked modules with missing or inconsistent
taint_sinks tables."""


class NoSinkTable:
    name = "hooks without sinks"
    pre_hooks = ["SSTORE"]

    def _execute(self, state):
        return []


class StaleSinkTable:
    name = "sink key outside the hook lists"
    pre_hooks = ["CALL"]
    # DELEGATECALL is never hooked -> dead entry; (0, "x") is not a
    # tuple of ints
    taint_sinks = {"DELEGATECALL": (), "CALL": (0, "x")}

    def _execute(self, state):
        return []
