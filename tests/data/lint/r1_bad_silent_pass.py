"""R1 bad fixture: broad + silent handler inside a function."""


def drain(queue):
    for item in queue:
        try:
            item.flush()
        except Exception:
            pass
