"""R5 clean fixture: declared-knob reads and non-MYTHRIL_TPU_* env
access are both fine."""

import os

LANES = os.environ.get("MYTHRIL_TPU_LANES", "128")
HOME = os.environ.get("HOME", "/root")
SHELL = os.getenv("SHELL")
