"""R6 bad fixture: reader-side calls (exporter / view surface) with
literal names that are not declared in the registry must fire, same as
emitters — a typo'd scrape silently renders a zero forever."""

from mythril_tpu.observe import metrics
from mythril_tpu.observe.metrics import quantile


def scrape():
    total = metrics.value("serve.requsts")  # typo: serve.requests
    p95 = quantile("dispatch.flush.latentcy_ms", 0.95)  # typo: latency_ms
    hist = metrics.histogram("frontier.telemetry.op_clas")  # typo: op_class
    return total, p95, hist
