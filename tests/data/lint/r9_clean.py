"""R9 fixture: consumes the shared absint verdicts; generic hex
parsing stays legal."""


def load_bytecode(text: str) -> bytes:
    # generic hex parse without an instruction `argument` is fine
    return bytes.fromhex(text) if text else b""


def parse_address(text: str) -> int:
    return int(text, 16)


def screen_branch(code, jumpi_pc):
    from mythril_tpu.smt.solver import cfa_screen

    # the blessed path: read the memoized value-range verdicts
    verdict = cfa_screen.jumpi_verdict(code, jumpi_pc)
    if verdict is not None:
        return verdict
    return cfa_screen.loop_bound_at(code, jumpi_pc)
