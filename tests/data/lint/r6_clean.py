"""R6 clean fixture: declared-metric emissions are fine; `.observe(...)`
on objects that are NOT the metrics module (jax tracers, watchdogs) and
dynamic names are out of scope."""

from mythril_tpu.observe import metrics


class Watcher:
    def observe(self, event):
        return event


def emit(watcher: Watcher, name: str):
    metrics.inc("solver.queries")
    metrics.set_gauge("solver.last_query_clauses", 42)
    metrics.observe("dispatch.flush.occupancy", 16)
    watcher.observe("anything.goes")  # not the metrics module
    metrics.set_value(name, 0)  # dynamic-name facade path: runtime contract


def read(name: str):
    total = metrics.value("serve.requests")
    p95 = metrics.quantile("dispatch.flush.latency_ms", 0.95)
    hist = metrics.histogram("frontier.telemetry.op_class", label="ADD")
    per_label = metrics.labels("frontier.telemetry.op_class")
    dynamic = metrics.value(name)  # dynamic-name read: runtime contract
    return total, p95, hist, per_label, dynamic
