"""R6 bad fixture: a counter-track decode loop publishing per-chunk
telemetry deltas under metric names missing from the observe registry —
the shape parallel/frontier.py's _publish_telemetry has, with typo'd /
undeclared names."""

from mythril_tpu.observe import metrics, trace


def publish_chunk(op_deltas, lifecycle, running):
    metrics.inc("frontier.telemetry.excuted", int(op_deltas.sum()))
    metrics.set_gauge("frontier.telemetry.occupancy_pct", running)
    for name, count in lifecycle.items():
        # dynamic label is fine; the literal metric name here is not
        metrics.observe("frontier.telemtry.lifecycle", count, label=name)
    trace.counter("frontier.lanes", running=running)  # not a metric: ok
