"""R5 bad fixture: undeclared knobs via os.getenv and a setdefault
write (setting an undeclared knob is the same typo one step earlier)."""

import os


def configure():
    os.environ.setdefault("MYTHRIL_TPU_MISSPELLED", "1")
    return os.getenv("MYTHRIL_TPU_NOT_A_KNOB", "1")
