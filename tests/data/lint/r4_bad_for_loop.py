"""R4 bad fixture: a table-densification for-loop carrying one real
mnemonic and one typo'd one."""

HANDLERS = {}


def register(table):
    for name in ("ADD", "MYSTERYOP"):
        HANDLERS[name] = table.lookup(name)
