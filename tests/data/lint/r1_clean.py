"""R1 clean fixture: broad excepts are fine when they are not silent,
and silent excepts are fine when they are narrow."""

import logging

log = logging.getLogger(__name__)


def load(path):
    try:
        with open(path) as handle:
            return handle.read()
    except Exception:
        log.warning("load failed: %s", path)
        raise


def probe(device):
    try:
        return device.kind
    except AttributeError:
        pass
    return None
