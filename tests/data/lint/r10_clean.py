"""R10 clean fixture: a gas table in exact parity with ops/opcodes.py.

Built from the opcode schedule itself (standalone file-path load, no
package import), so it cannot drift — the rule must stay quiet here.
"""

import importlib.util
import os

_REPO = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))
_spec = importlib.util.spec_from_file_location(
    "_r10_fixture_opcodes",
    os.path.join(_REPO, "mythril_tpu", "ops", "opcodes.py"))
_ops = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_ops)

STATIC_GAS = {name: meta[_ops.GAS][0]
              for name, meta in _ops.OPCODES.items()}
