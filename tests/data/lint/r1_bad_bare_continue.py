"""R1 bad fixture: bare except whose body is only `continue`, plus a
module-level broad `...` swallow."""


def poll(sources):
    results = []
    for src in sources:
        try:
            results.append(src.read())
        except:  # noqa: E722 - deliberately bare for the fixture
            continue
    return results


try:
    import fictional_accelerator_backend  # noqa: F401
except BaseException:
    ...
