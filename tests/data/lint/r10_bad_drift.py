"""R10 bad fixture: one of each parity-drift class against the real
ops/opcodes.py schedule — a mispriced mnemonic (MUL), a declared opcode
missing from the table (SHL), and a priced name that is not an opcode
(WARPSPEED)."""

import importlib.util
import os

_REPO = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))
_spec = importlib.util.spec_from_file_location(
    "_r10_bad_fixture_opcodes",
    os.path.join(_REPO, "mythril_tpu", "ops", "opcodes.py"))
_ops = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_ops)

STATIC_GAS = {name: meta[_ops.GAS][0]
              for name, meta in _ops.OPCODES.items()}
STATIC_GAS["MUL"] = 4        # price drift: schedule says 5
del STATIC_GAS["SHL"]        # declared opcode left unpriced
STATIC_GAS["WARPSPEED"] = 1  # priced, but not an opcode
