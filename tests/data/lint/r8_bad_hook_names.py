"""R8 bad fixture: hook lists naming opcodes that do not exist."""

EXTRA_OPS = ["CALL", "BOGUSOP"]


class MistypedHooks:
    name = "mistyped hooks"
    pre_hooks = ["JUMP", "NOTANOP"]
    post_hooks = EXTRA_OPS + ["SSTORE"]
    taint_sinks = {"JUMP": (), "CALL": (), "SSTORE": ()}

    def _execute(self, state):
        return []
