"""R9 fixture: statically re-simulates stack heights from the opcode
table instead of reading the CFA's entry_height."""

from mythril_tpu.ops import opcodes


def simulate_heights(instruction_list):
    height = 0
    heights = []
    for ins in instruction_list:
        heights.append(height)
        _, pops, pushes, _ = opcodes.opcodes[ins.op_code]
        # the flagged idiom: arithmetic over pushes/pops
        height = height - pops + pushes
    return heights
