"""R6 bad fixture: emissions naming metrics missing from the observe
registry, through a module alias (inc / set_gauge / observe)."""

from mythril_tpu.observe import metrics


def emit():
    metrics.inc("solver.warp_speed")
    metrics.set_gauge("frontier.vibes", 11)
    metrics.observe("dispatch.flux_capacitance", 1.21)
