"""R9 fixture: folds PUSH immediates locally instead of reading the
absint tables."""


def resolve_constant_target(instruction_list, index):
    push = instruction_list[index]
    # (1) attribute-style immediate fold
    return int(push.argument, 16)


def fold_selector(instruction):
    # (2) dict-style immediate fold
    return int(instruction["argument"], 16) >> 224


class Interval:  # (3) ad-hoc interval domain class
    def __init__(self, lo, hi):
        self.lo = lo
        self.hi = hi
