"""R4 clean fixture: every referenced mnemonic exists in the real
ops/opcodes.py table, across all three reference shapes."""

HANDLERS = {}


def dispatch(op, O, state):
    if is_op(op, "ADD"):
        return state + 1
    if op_in(op, "MLOAD", "MSTORE"):
        return state
    if op == O["SSTORE"]:
        return state - 1
    return state


def register(table):
    for name in ("PUSH1", "DUP1", "SWAP1"):
        HANDLERS[name] = table.lookup(name)


def is_op(op, name):
    return False


def op_in(op, *names):
    return False
