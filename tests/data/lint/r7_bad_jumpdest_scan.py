"""R7 fixture: re-implements jump-target resolution three ways."""


class FakeDisassembly:
    def __init__(self, instruction_list):
        # (1) assignment to the canonical set name
        self.valid_jump_destinations = {
            ins.address for ins in instruction_list
            if ins.op_code == "JUMPDEST"}  # (2) comprehension scan too


def collect_targets(instruction_list):
    # (3) longhand for-loop collection
    targets = set()
    for ins in instruction_list:
        if ins.op_code == "JUMPDEST":
            targets.add(ins.address)
    return targets
