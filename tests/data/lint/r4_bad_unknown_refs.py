"""R4 bad fixture: interpreter-style dispatch referencing mnemonics that
do not exist in ops/opcodes.py — the comparisons can never match."""


def dispatch(op, O, state):
    if is_op(op, "BOGUSADD"):
        return state + 1
    if op == O["NOTANOP"]:
        return state - 1
    return state


def is_op(op, name):
    return False
