"""R5 bad fixture: reads of MYTHRIL_TPU_* names missing from the
tpu_config registry, via .get and subscript access."""

import os

TURBO = os.environ.get("MYTHRIL_TPU_TURBO", "0")


def speed():
    return os.environ["MYTHRIL_TPU_SPEED"]
