"""R8 clean fixture: well-formed hook lists (literal, named constant,
and ``+``-concatenated) with a matching taint_sinks table."""

CALL_OPS = ["CALL", "DELEGATECALL"]


class WellFormedModule:
    name = "well-formed module"
    pre_hooks = CALL_OPS + ["SSTORE"]
    post_hooks = ["CALL"]
    taint_sinks = {"CALL": (), "DELEGATECALL": (0,), "SSTORE": (0, 1)}

    def _execute(self, state):
        return []


class HooklessHelper:
    """No hooks at all — the rule must not demand a sink table."""

    name = "hookless helper"

    def _execute(self, state):
        return []


class EmptyHookBase:
    """Empty hook lists (the DetectionModule base shape)."""

    pre_hooks = []
    post_hooks = []
    taint_sinks = {}
