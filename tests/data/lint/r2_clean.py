"""R2 clean fixture: goes through the dispatch queue; a bare reference
to the entry point (monkeypatch target, no call) is also fine."""

from mythril_tpu.parallel import jax_solver
from mythril_tpu.smt.solver import dispatch

PATCH_TARGET = jax_solver.solve_cnf_device


def decide(cnf):
    return dispatch.solve(cnf)
