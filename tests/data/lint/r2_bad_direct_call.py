"""R2 bad fixture: plain-name call to the device solver entry point."""

from mythril_tpu.parallel.jax_solver import solve_cnf_device


def decide(cnf):
    return solve_cnf_device(cnf)
