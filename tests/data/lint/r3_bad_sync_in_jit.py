"""R3 bad fixture: implicit device->host syncs inside traced functions —
a .item() under a @jax.jit decorator and a host-numpy call inside a
function traced via the jax.jit(...) wrapper form."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def worst_lane(scores):
    return scores.argmin().item()


def _normalize(x):
    total = np.sum(x)
    return x / total


normalize = jax.jit(_normalize)


def run(scores):
    return normalize(jnp.asarray(scores)), worst_lane(jnp.asarray(scores))
