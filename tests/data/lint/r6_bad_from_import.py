"""R6 bad fixture: an emitter function from-imported (and aliased) out of
the metrics module still gets audited."""

from mythril_tpu.observe.metrics import inc as bump


def emit():
    bump("solver.queries_typo")
