"""R3 clean fixture: per-lane data flow stays on device (jnp.where /
lax.fori_loop), host numpy only touches trace-time constants at module
scope, and the driver never pulls scalars back."""

import jax
import jax.numpy as jnp
import numpy as np

_TABLE = np.arange(16, dtype=np.int32)
_LIMIT = int("40", 16)


@jax.jit
def step(lane):
    bumped = jnp.where(lane > 0, lane - 1, lane)

    def body(_, acc):
        return acc + bumped

    return jax.lax.fori_loop(0, 4, body, jnp.zeros_like(bumped))


def drive(lanes):
    return step(jnp.asarray(lanes, dtype=jnp.int32))
