"""R2 bad fixture: attribute-style call to the batched entry point."""

from mythril_tpu.parallel import jax_solver


def decide_all(cnfs):
    return jax_solver.solve_cnf_device_batch(cnfs)
