"""R7 fixture: consumes the CFA tables; point checks stay legal."""


def is_jumpdest(instruction) -> bool:
    # a point check on ONE instruction is not set construction
    return instruction.op_code == "JUMPDEST"


def screen(disassembly, code, jump_address):
    from mythril_tpu.smt.solver import cfa_screen

    # the blessed path: read the shared tables
    verdict = cfa_screen.screen_jump_target(code, jump_address)
    if verdict is None:
        index = disassembly.index_of_address(jump_address)
        return index is not None and \
            disassembly.instruction_list[index].op_code == "JUMPDEST"
    return verdict
