"""R3 bad fixture: Python branching on a traced value inside a vmapped
function, plus unjustified explicit sync sites in the host driver."""

import jax
import jax.numpy as jnp


@jax.vmap
def step(lane):
    if jnp.any(lane > 0):
        return lane - 1
    return lane


def drive(lanes):
    out = step(lanes)
    while int(jnp.sum(out)) > 0:
        out = step(out)
    return jax.device_get(out)
