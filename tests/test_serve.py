"""Serve daemon tests: protocol framing/validation (jax-free), the
warm-set manifest, service request handling, the warm/cold per-request
accounting, and the coarse-bucketing A/B assertion.

Fast tests never invoke a real jitted runner — device runners are faked
(XLA compiles minutes per clause-shape bucket on CPU); the one
real-XLA end-to-end check is @pytest.mark.slow."""

import io
import json
import socket
import threading
import time

import numpy as np
import pytest

from mythril_tpu.observe import export, metrics, slog, trace
from mythril_tpu.parallel import jax_solver
from mythril_tpu.serve import client as serve_client
from mythril_tpu.serve import daemon, protocol, warmset
from mythril_tpu.serve.admission import AdmissionQueue, _Waiter
from mythril_tpu.serve.service import AnalysisService


@pytest.fixture(autouse=True)
def _clean_observability():
    metrics.reset()
    trace.reset()
    slog.reset()
    export.reset_ring()
    yield
    metrics.reset()
    trace.reset()
    slog.reset()
    export.reset_ring()


def _fake_batch_runner(chunk, forced_depth):
    """Stands in for the jitted vmapped runner: decides every lane UNSAT
    without touching jax.jit (shape accounting still goes through
    _run_accounted, which is what these tests measure)."""

    def run(state, lits, valid, order):
        return state._replace(status=np.full(
            np.asarray(state.status).shape, jax_solver.S_UNSAT,
            dtype=np.int8))

    return run


def _fresh_shapes(monkeypatch):
    monkeypatch.setattr(jax_solver, "_SHAPES_RUN", set())
    monkeypatch.setattr(jax_solver, "_get_batch_runner", _fake_batch_runner)
    monkeypatch.setattr(jax_solver, "_get_runner",
                        lambda chunk, fd: _fake_batch_runner(chunk, fd))


# -- protocol: framing + validation (stdlib only) ------------------------------------


def test_parse_ping_and_auto_id():
    request = protocol.parse_request('{"op": "ping"}')
    assert request.op == "ping"
    assert str(request.id).startswith("req-")


def test_parse_analyze_normalizes_defaults():
    request = protocol.parse_request(json.dumps(
        {"op": "analyze", "id": "r9", "code": "0x6001600055"}))
    assert request.id == "r9"
    assert request.params["code"] == "0x6001600055"
    assert request.params["transaction_count"] == 2
    assert request.params["strategy"] == "bfs"
    assert request.params["max_depth"] == 128
    assert request.params["deadline_ms"] is None


def test_parse_rejects_bad_json_and_non_objects():
    with pytest.raises(protocol.ProtocolError) as err:
        protocol.parse_request("{nope")
    assert err.value.code == "bad_json"
    with pytest.raises(protocol.ProtocolError) as err:
        protocol.parse_request("[1, 2]")
    assert err.value.code == "bad_request"
    with pytest.raises(protocol.ProtocolError) as err:
        protocol.parse_request(b"\xff\xfe not utf8")
    assert err.value.code == "bad_json"


def test_parse_rejects_unknown_op_but_keeps_id():
    with pytest.raises(protocol.ProtocolError) as err:
        protocol.parse_request('{"op": "explode", "id": "x1"}')
    assert err.value.code == "unknown_op"
    assert err.value.request_id == "x1"


@pytest.mark.parametrize("payload,fragment", [
    ({"op": "analyze"}, "code"),
    ({"op": "analyze", "code": "abc"}, "odd hex"),
    ({"op": "analyze", "code": "zz"}, "not valid hex"),
    ({"op": "analyze", "code": "60", "transaction_count": 0}, "[1, 16]"),
    ({"op": "analyze", "code": "60", "transaction_count": True}, "[1, 16]"),
    ({"op": "analyze", "code": "60", "strategy": "psychic"}, "strategy"),
    ({"op": "analyze", "code": "60", "solver": "z3"}, "solver"),
    ({"op": "analyze", "code": "60", "max_depth": 0}, "max_depth"),
])
def test_parse_rejects_bad_analyze_fields(payload, fragment):
    with pytest.raises(protocol.ProtocolError) as err:
        protocol.parse_request(json.dumps(payload))
    assert err.value.code == "bad_request"
    assert fragment in err.value.message


@pytest.mark.parametrize("deadline", [0, -5, True, 86_400_001])
def test_parse_rejects_bad_deadlines(deadline):
    with pytest.raises(protocol.ProtocolError) as err:
        protocol.parse_request(json.dumps(
            {"op": "analyze", "code": "60", "deadline_ms": deadline}))
    assert err.value.code == "bad_request"
    assert "deadline_ms" in err.value.message


def test_parse_accepts_fractional_deadline():
    request = protocol.parse_request(json.dumps(
        {"op": "analyze", "code": "60", "deadline_ms": 1500.5}))
    assert request.params["deadline_ms"] == 1500.5


def test_oversized_line_is_line_too_long(monkeypatch):
    monkeypatch.setattr(protocol, "MAX_LINE_BYTES", 64)
    with pytest.raises(protocol.ProtocolError) as err:
        protocol.parse_request(b"x" * 65)
    assert err.value.code == "line_too_long"


def test_read_lines_reassembles_split_frames(monkeypatch):
    # one frame split across reads, two frames in one read, and a
    # trailing unterminated frame — all must come out intact
    chunks = [b'{"op": "pi', b'ng"}\n{"a": 1}\n{"b"', b": 2}"]

    class Chunked:
        def read(self, _n):
            return chunks.pop(0) if chunks else b""

    frames = list(protocol.read_lines(Chunked()))
    assert frames == [b'{"op": "ping"}', b'{"a": 1}', b'{"b": 2}']


def test_read_lines_bounds_runaway_frames(monkeypatch):
    # a frame that spans many reads without a newline must not buffer
    # unboundedly: it is truncated to MAX+1 (so its parse fails loudly
    # as line_too_long) and the remainder is dropped until the newline
    monkeypatch.setattr(protocol, "MAX_LINE_BYTES", 16)
    chunks = [b"a" * 10, b"a" * 10, b"a" * 80, b"a\n", b'{"op": "ping"}\n']

    class Chunked:
        def read(self, _n):
            return chunks.pop(0) if chunks else b""

    frames = list(protocol.read_lines(Chunked()))
    assert len(frames) == 2
    assert len(frames[0]) == 17  # truncated to MAX+1: parse fails loudly
    assert frames[1] == b'{"op": "ping"}'


def test_iter_requests_survives_bad_lines():
    stream = io.BytesIO(b'{"op": "ping"}\n\nnot json\n{"op": "status"}\n')
    items = list(protocol.iter_requests(stream))
    assert [type(i).__name__ for i in items] == \
        ["Request", "ProtocolError", "Request"]
    assert items[1].code == "bad_json"


def test_encode_is_single_sorted_line():
    line = protocol.encode({"z": 1, "a": {"k": "v"}, "id": "r"})
    assert line.endswith("\n") and "\n" not in line[:-1]
    assert line.index('"a"') < line.index('"id"') < line.index('"z"')


# -- warm-set manifest ---------------------------------------------------------------


def test_manifest_round_trip_and_union_merge(tmp_path):
    path = str(tmp_path / "warmset.json")
    assert warmset.load_manifest(path) == []
    first = [("batch", 256, 5, 1, 1024, 4, 32)]
    assert warmset.save_manifest(path, first) == 1
    second = [("single", 1, 256, 5, 1, 1024, 32),
              ("batch", 256, 5, 1, 1024, 4, 32)]
    assert warmset.save_manifest(path, second) == 2  # union, not replace
    assert warmset.load_manifest(path) == sorted(set(first + second))


def test_manifest_tolerates_garbage(tmp_path):
    path = tmp_path / "warmset.json"
    path.write_text("{not json")
    assert warmset.load_manifest(str(path)) == []
    path.write_text(json.dumps({"version": 99, "shapes": [["batch", 1]]}))
    assert warmset.load_manifest(str(path)) == []
    path.write_text(json.dumps({
        "version": 1,
        "shapes": [["batch", 256, 5, 1, 1024, 4, 32],
                   "not-a-list", [123], ["single", "not-an-int"]]}))
    assert warmset.load_manifest(str(path)) == \
        [("batch", 256, 5, 1, 1024, 4, 32)]


def test_warm_shape_key_rejects_garbage_without_jax_work():
    assert not jax_solver.warm_shape_key("bogus")
    assert not jax_solver.warm_shape_key(("bogus", 1, 2, 3))
    assert not jax_solver.warm_shape_key(("single", 1, 256, 5, 0, 16, 8))
    assert not jax_solver.warm_shape_key(  # tiles beyond the sanity bound
        ("single", 1, 256, 5, 1 << 20, 16, 8))
    assert not jax_solver.warm_shape_key(
        ("batch", 256, 5, 1, 16, 1 << 20, 8))


def test_warmup_then_solve_reuses_bucket(tmp_path, monkeypatch):
    """The tentpole mechanism, minus XLA: a manifest-warmed bucket makes
    the first REAL solve of that shape a reuse, not a compile."""
    _fresh_shapes(monkeypatch)
    path = str(tmp_path / "warmset.json")

    # run one fake-runner solve to discover its shape key, persist it
    jax_solver.solve_cnf_device_batch([([[1]], 1)], n_probes=2, chunk=4)
    observed = jax_solver.observed_shape_keys()
    assert len(observed) == 1
    warmset.save_manifest(path, observed)

    # fresh process-equivalent: empty shape cache, warm from manifest
    monkeypatch.setattr(jax_solver, "_SHAPES_RUN", set())
    metrics.reset()
    ws = warmset.WarmSet(path)
    assert ws.warmup() == 1
    assert metrics.value("serve.warmed_buckets") == 1
    assert metrics.value("xla.bucket_compiles") == 1  # paid by warmup

    jax_solver.solve_cnf_device_batch([([[1]], 1)], n_probes=2, chunk=4)
    assert metrics.value("xla.bucket_compiles") == 1  # no new compile
    assert metrics.value("xla.bucket_reuses") == 1


# -- service: request handling -------------------------------------------------------


def _service(**overrides):
    defaults = dict(manifest_path=None, warmup=False, max_inflight=2)
    defaults.update(overrides)
    return AnalysisService(**defaults)


def test_service_control_ops():
    service = _service()
    pong = service.handle(protocol.parse_request('{"op": "ping", "id": 1}'))
    assert pong["ok"] and pong["pong"] and pong["id"] == 1
    status = service.handle(protocol.parse_request('{"op": "status"}'))
    assert status["ok"] and status["max_inflight"] == 2
    assert status["warmset"]["warmed_buckets"] == 0
    down = service.handle(protocol.parse_request('{"op": "shutdown"}'))
    assert down["ok"] and down["shutdown"]
    late = service.handle(protocol.parse_request('{"op": "ping"}'))
    assert not late["ok"] and late["error"]["code"] == "shutting_down"


def test_service_replies_to_protocol_errors():
    service = _service()
    reply = service.handle(
        protocol.ProtocolError("bad_json", "nope", request_id="e1"))
    assert reply == {"id": "e1", "ok": False,
                     "error": {"code": "bad_json", "message": "nope"}}
    assert metrics.value("serve.request_errors") == 1


def test_service_sheds_bulk_when_queue_full(monkeypatch):
    """With the single slot busy and the queue at capacity with an
    interactive waiter, a bulk arrival is shed with a typed
    ``overloaded`` error carrying a retry hint — while the queued
    interactive request still completes."""
    service = _service(max_inflight=1)
    service._admission = AdmissionQueue(1, capacity=1, retry_after_ms=250)
    entered = threading.Event()
    release = threading.Event()

    def slow_analysis(params):
        entered.set()
        assert release.wait(30)
        return _fake_payload(params)

    monkeypatch.setattr(service, "_run_analysis", slow_analysis)
    replies = {}

    def run(tag, frame):
        replies[tag] = service.handle(protocol.parse_request(frame))

    slow = threading.Thread(target=run, args=(
        "slow", '{"op": "analyze", "id": "s1", "code": "60"}'), daemon=True)
    slow.start()
    assert entered.wait(10)  # the lone slot is now occupied
    queued = threading.Thread(target=run, args=(
        "queued", '{"op": "analyze", "id": "q1", "code": "6001"}'),
        daemon=True)
    queued.start()
    deadline = time.monotonic() + 10
    while sum(service._admission.depths().values()) < 1:
        assert time.monotonic() < deadline, "waiter never queued"
        time.sleep(0.01)
    reply = service.handle(protocol.parse_request(
        '{"op": "analyze", "id": "b1", "code": "6002", '
        '"priority": "bulk"}'))
    release.set()
    slow.join(timeout=10)
    queued.join(timeout=10)
    assert not reply["ok"] and reply["error"]["code"] == "overloaded"
    assert reply["error"]["retry_after_ms"] >= 250
    assert metrics.value("serve.busy_rejections") == 1
    assert metrics.value("serve.shed.overload") == 1
    assert replies["slow"]["ok"] and replies["queued"]["ok"]


def test_service_analysis_failure_is_a_reply_not_a_crash(monkeypatch):
    service = _service()
    monkeypatch.setattr(service, "_run_analysis",
                        lambda params: (_ for _ in ()).throw(
                            RuntimeError("engine exploded")))
    reply = service.handle(protocol.parse_request(
        '{"op": "analyze", "id": "boom", "code": "60"}'))
    assert not reply["ok"]
    assert reply["error"]["code"] == "analysis_failed"
    assert "engine exploded" in reply["error"]["message"]
    assert metrics.value("serve.request_errors") == 1
    assert metrics.value("serve.requests") == 1


def test_second_request_hits_warm_buckets(monkeypatch):
    """Per-request warm/cold accounting: request one compiles its
    bucket, request two reuses it — zero new compiles (the serve
    acceptance assertion, with the runner faked instead of jitted)."""
    _fresh_shapes(monkeypatch)
    service = _service()

    def fake_analysis(params):
        jax_solver.solve_cnf_device_batch([([[1]], 1)], n_probes=2, chunk=4)
        return {"issue_count": 0, "incomplete": False, "coverage": {},
                "report": {"success": True, "error": None, "issues": []}}

    monkeypatch.setattr(service, "_run_analysis", fake_analysis)
    first = service.handle(protocol.parse_request(
        '{"op": "analyze", "id": "c1", "code": "60"}'))
    second = service.handle(protocol.parse_request(
        '{"op": "analyze", "id": "c2", "code": "60"}'))
    assert first["ok"] and second["ok"]
    # exec cache: the fake runner's bucket misses the (empty) persistent
    # store on first touch; the second request reuses in-process warmth
    # and never consults it
    assert first["warm"] == {"cold_buckets": 1, "warm_hits": 0,
                             "exec_hits": 0, "exec_misses": 1}
    assert second["warm"] == {"cold_buckets": 0, "warm_hits": 1,
                              "exec_hits": 0, "exec_misses": 0}
    assert metrics.value("serve.requests") == 2
    hist = metrics.histogram("serve.request_ms")
    assert hist is not None and hist.count == 2


# -- observability: scrape ops, correlation ids, concurrency ------------------------


def _fake_payload(params):
    return {"issue_count": 0, "incomplete": False, "coverage": {},
            "report": {"issues": []}}


def test_metrics_op_returns_exposition_and_ring_tail(monkeypatch):
    service = _service()
    monkeypatch.setattr(service, "_run_analysis", _fake_payload)
    analyze = service.handle(protocol.parse_request(
        '{"op": "analyze", "id": "a", "code": "6001"}'))
    assert analyze["ok"]
    reply = service.handle(protocol.parse_request(
        '{"op": "metrics", "id": "m"}'))
    assert reply["ok"]
    assert reply["content_type"].startswith("text/plain; version=0.0.4")
    assert "mythril_tpu_serve_requests_total 1" in reply["exposition"]
    assert metrics.value("serve.metrics_scrapes") == 1
    # ring carries one entry per finished analyze + one per scrape
    entries = reply["ring"]["entries"]
    assert [e.get("request_id") or e.get("scrape") for e in entries] == \
        ["a", "m"]
    assert entries[0]["correlation_id"] == analyze["correlation_id"]
    assert entries[0]["metrics"]["serve.requests"] == 1


def test_scrapes_answer_while_engine_lock_is_held(monkeypatch):
    """A /healthz or /metrics probe during a long analyze must answer
    immediately: both ops are routed before admission and never take
    the engine lock."""
    service = _service()
    entered = threading.Event()
    release = threading.Event()

    def slow_analysis(params):
        entered.set()
        assert release.wait(30)
        return _fake_payload(params)

    monkeypatch.setattr(service, "_run_analysis", slow_analysis)
    worker = threading.Thread(
        target=service.handle,
        args=(protocol.parse_request(
            '{"op": "analyze", "id": "slow", "code": "6001"}'),),
        daemon=True)
    worker.start()
    assert entered.wait(10)  # engine lock is now held
    results = {}

    def probe():
        results["healthz"] = service.handle(
            protocol.parse_request('{"op": "healthz", "id": "h"}'))
        results["metrics"] = service.handle(
            protocol.parse_request('{"op": "metrics", "id": "m"}'))

    prober = threading.Thread(target=probe, daemon=True)
    prober.start()
    prober.join(timeout=5)
    blocked = prober.is_alive()
    release.set()
    worker.join(timeout=10)
    assert not blocked, "scrape blocked behind the engine lock"
    assert results["healthz"]["ok"] and results["healthz"]["healthy"]
    assert "exposition" in results["metrics"]


def test_shed_bounce_counts_and_correlates(tmp_path):
    """An overload shed still counts as an answered request AND a
    rejection, and its reply + structured-log line share one
    correlation id minted at admission."""
    sink = str(tmp_path / "shed.slog")
    slog.enable(sink)
    service = _service(max_inflight=1)
    queue = AdmissionQueue(1, capacity=1, retry_after_ms=100)
    service._admission = queue
    assert queue.try_acquire()  # the lone slot is busy
    # the queue is already at capacity with an interactive waiter, so
    # the arriving bulk request is itself the lowest-priority victim
    queue._seq += 1
    queue._waiters.append(_Waiter("interactive", None, queue._seq))
    try:
        reply = service.handle(protocol.parse_request(
            '{"op": "analyze", "id": "b1", "code": "60", '
            '"priority": "bulk"}'))
    finally:
        queue.release()
    assert not reply["ok"] and reply["error"]["code"] == "overloaded"
    assert reply["error"]["retry_after_ms"] >= 100
    cid = reply["correlation_id"]
    assert cid
    assert metrics.value("serve.requests") == 1
    assert metrics.value("serve.busy_rejections") == 1
    records = [json.loads(line) for line in open(sink, encoding="utf-8")]
    shed = [r for r in records if r["event"] == "serve.shed"]
    assert len(shed) == 1
    assert shed[0]["cid"] == cid and shed[0]["request_id"] == "b1"
    assert shed[0]["priority"] == "bulk" and shed[0]["reason"] == "overload"


def test_analyze_reply_and_slog_share_correlation_id(tmp_path,
                                                     monkeypatch):
    sink = str(tmp_path / "run.slog")
    slog.enable(sink)
    service = _service()
    monkeypatch.setattr(service, "_run_analysis", _fake_payload)
    reply = service.handle(protocol.parse_request(
        '{"op": "analyze", "id": "a1", "code": "6001"}'))
    assert reply["ok"]
    cid = reply["correlation_id"]
    assert cid
    records = [json.loads(line) for line in open(sink, encoding="utf-8")]
    by_event = {r["event"]: r for r in records}
    assert by_event["serve.admitted"]["cid"] == cid
    assert by_event["serve.reply"]["cid"] == cid
    assert by_event["serve.reply"]["ok"] is True
    assert by_event["serve.reply"]["issues"] == 0


def test_http_shim_serves_healthz_and_metrics(monkeypatch):
    from urllib.request import urlopen

    from mythril_tpu.serve import http_shim

    service = _service()
    monkeypatch.setattr(service, "_run_analysis", _fake_payload)
    ready = threading.Event()
    thread = threading.Thread(
        target=http_shim.serve_http, args=(service,),
        kwargs={"port": 0, "ready_event": ready}, daemon=True)
    thread.start()
    assert ready.wait(10)
    base = f"http://127.0.0.1:{service.http_port}"
    try:
        with urlopen(base + "/healthz", timeout=10) as response:
            health = json.loads(response.read())
        assert health["ok"] and health["healthy"]
        with urlopen(base + "/metrics", timeout=10) as response:
            content_type = response.headers["Content-Type"]
            text = response.read().decode("utf-8")
        assert content_type.startswith("text/plain; version=0.0.4")
        assert "mythril_tpu_serve_requests_total" in text
        assert "# HELP mythril_tpu_serve_requests " in text
    finally:
        service.shutting_down.set()
        thread.join(timeout=10)
    assert not thread.is_alive()


def test_stdio_loop_replies_per_frame_and_honors_shutdown(monkeypatch):
    service = _service()
    monkeypatch.setattr(
        service, "_run_analysis",
        lambda params: {"issue_count": 0, "incomplete": False,
                        "coverage": {}, "report": {"issues": []}})
    stdin = io.BytesIO(
        b'{"op": "ping", "id": "p"}\n'
        b'garbage\n'
        b'{"op": "analyze", "id": "a", "code": "6001"}\n'
        b'{"op": "shutdown", "id": "s"}\n'
        b'{"op": "ping", "id": "never-read"}\n')
    stdout = io.BytesIO()
    answered = daemon.serve_stdio(service, stdin=stdin, stdout=stdout)
    replies = [json.loads(line) for line in stdout.getvalue().splitlines()]
    assert answered == 4  # loop stops at shutdown, last ping unread
    assert [r["id"] for r in replies] == ["p", None, "a", "s"]
    assert replies[1]["error"]["code"] == "bad_json"
    assert replies[2]["ok"] and replies[2]["issue_count"] == 0


def test_socket_daemon_roundtrip(tmp_path, monkeypatch):
    service = _service()
    monkeypatch.setattr(
        service, "_run_analysis",
        lambda params: {"issue_count": 0, "incomplete": False,
                        "coverage": {}, "report": {"issues": []}})
    path = str(tmp_path / "serve.sock")
    ready = threading.Event()
    thread = threading.Thread(
        target=daemon.serve_socket, args=(service,),
        kwargs={"socket_path": path, "ready_event": ready}, daemon=True)
    thread.start()
    assert ready.wait(10)
    replies = serve_client.roundtrip(
        [{"op": "ping", "id": "p"},
         {"op": "analyze", "id": "a", "code": "6001"},
         {"op": "shutdown", "id": "s"}],
        socket_path=path, timeout=30)
    assert [r["id"] for r in replies] == ["p", "a", "s"]
    assert all(r["ok"] for r in replies)
    thread.join(timeout=10)
    assert not thread.is_alive()


def test_client_raises_without_daemon(tmp_path):
    with pytest.raises(serve_client.ServeClientError):
        serve_client.request({"op": "ping"},
                             socket_path=str(tmp_path / "absent.sock"),
                             timeout=2)


def test_stale_socket_file_is_reclaimed(tmp_path, monkeypatch):
    # a crashed daemon leaves the socket file behind; the next daemon
    # must probe, unlink, and bind — not die on EADDRINUSE
    service = _service()
    path = str(tmp_path / "serve.sock")
    stale = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    stale.bind(path)
    stale.close()  # closed without listen: connect() will fail => stale
    ready = threading.Event()
    thread = threading.Thread(
        target=daemon.serve_socket, args=(service,),
        kwargs={"socket_path": path, "ready_event": ready}, daemon=True)
    thread.start()
    assert ready.wait(10)
    reply = serve_client.request({"op": "shutdown"}, socket_path=path,
                                 timeout=10)
    assert reply["ok"]
    thread.join(timeout=10)


# -- coarse bucketing A/B (satellite: fewer, fatter buckets) -------------------------


def _corpus():
    """Clause-shape corpus spanning the realistic range: clause counts
    around tile boundaries, var counts across the pow2 tail the fine
    scheme fragments into."""
    rng = np.random.default_rng(7)
    corpus = []
    for n_clauses in (3, 17, 120, 700, 2100, 4100, 6000, 9000):
        for n_vars in (9, 40, 100, 300, 620, 1030, 2500, 5000):
            n_lits = int(rng.integers(1, 4))
            corpus.append(([list(range(1, n_lits + 1))] * n_clauses,
                           n_vars))
    return corpus


@pytest.mark.parametrize("scheme", ["coarse", "fine"])
def test_bucket_scheme_knob_selects_rounding(scheme, monkeypatch):
    monkeypatch.setenv("MYTHRIL_TPU_BUCKET_SCHEME", scheme)
    if scheme == "coarse":
        assert jax_solver._bucket_tiles(3) == 4
        assert jax_solver._bucket_vars(5) == jax_solver.COARSE_VARS_FLOOR
        assert jax_solver._bucket_vars(1025) == 4096
        assert jax_solver._bucket_batch(5) == 16
    else:
        assert jax_solver._bucket_tiles(3) == 4
        assert jax_solver._bucket_vars(5) == 8
        assert jax_solver._bucket_vars(1025) == 2048
        assert jax_solver._bucket_batch(5) == 8


def test_coarse_scheme_halves_corpus_bucket_compiles(monkeypatch):
    """The A/B satellite assertion: replaying one corpus through the
    solver compiles at most HALF as many buckets under the coarse
    scheme as under the fine scheme (bucket_compiles metric, fake
    runners — the bucket count is a pure shape-canonicalization
    property)."""
    compiles = {}
    for scheme in ("fine", "coarse"):
        monkeypatch.setenv("MYTHRIL_TPU_BUCKET_SCHEME", scheme)
        _fresh_shapes(monkeypatch)
        metrics.reset("xla.")
        for clauses, n_vars in _corpus():
            jax_solver.solve_cnf_device(clauses, n_vars, n_probes=2,
                                        chunk=4, max_steps=4)
        compiles[scheme] = metrics.value("xla.bucket_compiles")
    assert compiles["coarse"] >= 1
    assert compiles["coarse"] <= compiles["fine"] / 2, compiles


# -- end to end with real XLA (slow) -------------------------------------------------


@pytest.mark.slow
def test_e2e_second_contract_needs_no_new_compiles(tmp_path, monkeypatch):
    """Real-XLA acceptance: the second request to a warm daemon performs
    ZERO new XLA compilations for warmed buckets.

    This drives the real daemon loop, protocol, per-request compile/reuse
    accounting, and warmset persistence against genuine jit compiles —
    only the symbolic-execution layer is stubbed with a per-request
    device-batch solve, because a full `--solver jax` analysis compiles
    dozens of large buckets (hours of CPU XLA; that path is covered with
    fake runners above and by tools/serve_smoke.py with the CDCL solver).
    Both requests carry distinct CNFs that canonicalize into the same
    coarse bucket, so a cold bucket on request one MUST be a warm hit on
    request two — the executable, not the verdict cache, is what's reused.
    """
    # fresh accounting even if an earlier test in this process already
    # compiled this bucket (the jit cache itself cannot be evicted)
    monkeypatch.setattr(jax_solver, "_SHAPES_RUN", set())
    cnfs = iter([
        ([[1, 2], [-1, 2]], 2),
        ([[1, -2], [2], [-1, 2]], 3),
    ])
    service = _service(solver="jax",
                       manifest_path=str(tmp_path / "warmset.json"))

    def run_device_solve(params):
        clauses, n_vars = next(cnfs)
        (status, model), = jax_solver.solve_cnf_device_batch(
            [(clauses, n_vars)], n_probes=2, chunk=4, max_steps=64)
        return {"issue_count": 0, "incomplete": False,
                "status": int(status), "model": model}

    service._run_analysis = run_device_solve
    stdin = io.BytesIO(
        (json.dumps({"op": "analyze", "id": "c1", "code": "0x00",
                     "solver": "jax"}) + "\n"
         + json.dumps({"op": "analyze", "id": "c2", "code": "0x00",
                       "solver": "jax"}) + "\n").encode())
    stdout = io.BytesIO()
    daemon.serve_stdio(service, stdin=stdin, stdout=stdout)
    replies = [json.loads(line) for line in stdout.getvalue().splitlines()]
    assert all(r["ok"] for r in replies)
    first, second = replies
    assert first["warm"]["cold_buckets"] >= 1, first["warm"]
    assert second["warm"]["cold_buckets"] == 0, second["warm"]
    assert second["warm"]["warm_hits"] >= 1, second["warm"]
    assert second["status"] == jax_solver.S_SAT
    # the manifest now remembers every bucket this daemon compiled
    assert warmset.load_manifest(str(tmp_path / "warmset.json")) \
        == jax_solver.observed_shape_keys()


# ---------------------------------------------------------------------------
# fleet QoS: batch composition order and interactive preemption targeting


def test_fleet_ticket_sort_orders_priority_then_deadline():
    from mythril_tpu.serve.service import _FleetTicket

    bulk_late = _FleetTicket({"priority": "bulk", "deadline_ms": 9000}, "c1")
    interactive = _FleetTicket({"priority": "interactive"}, "c2")
    bulk_soon = _FleetTicket({"priority": "bulk", "deadline_ms": 1000}, "c3")
    no_priority = _FleetTicket({}, "c4")  # defaults to interactive

    group = [bulk_late, interactive, bulk_soon, no_priority]
    group.sort(key=_FleetTicket.sort_key)
    # interactive class first (arrival order breaks the tie), then bulk
    # by earliest deadline
    assert group == [interactive, no_priority, bulk_soon, bulk_late]


def test_fleet_preempt_targets_only_all_bulk_batches():
    from mythril_tpu.serve.service import _FleetBatcher, _FleetTicket

    metrics.reset()
    batcher = _FleetBatcher(service=object())
    bulk_batch = {
        "preempt": threading.Event(),
        "tickets": [_FleetTicket({"priority": "bulk"}, "b1"),
                    _FleetTicket({"priority": "bulk"}, "b2")],
    }
    mixed_batch = {
        "preempt": threading.Event(),
        "tickets": [_FleetTicket({"priority": "bulk"}, "m1"),
                    _FleetTicket({"priority": "interactive"}, "m2")],
    }
    batcher._inflight = [bulk_batch, mixed_batch]

    assert batcher.preempt_for_interactive() == 1
    # only the all-bulk batch was told to drain; the batch already
    # serving an interactive member keeps the engine
    assert bulk_batch["preempt"].is_set()
    assert not mixed_batch["preempt"].is_set()
    assert metrics.value("serve.fleet.preempted") == 1

    # idempotent: an already-preempted batch is not counted again
    assert batcher.preempt_for_interactive() == 0
    assert metrics.value("serve.fleet.preempted") == 1
