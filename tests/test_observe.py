"""Unit tests for mythril_tpu/observe/: the span tracer (Perfetto export,
disabled-mode fast path, ring-buffer drop accounting) and the typed
metrics registry (declared-name contract, counters/gauges/histograms,
SolverStatistics facade back-compat), plus one cheap end-to-end host-engine
run proving the exported trace is loadable and its spans cover the run.
"""

import json
import os

import pytest

from mythril_tpu.observe import metrics, trace
from mythril_tpu.smt.solver.solver_statistics import (FACADE_METRICS,
                                                      SolverStatistics,
                                                      stat_smt_query)


@pytest.fixture(autouse=True)
def _clean_observability(monkeypatch):
    """Tracer and metric store are process singletons: every test starts
    and ends from the never-touched state."""
    monkeypatch.delenv("MYTHRIL_TPU_TRACE", raising=False)
    monkeypatch.delenv("MYTHRIL_TPU_TRACE_BUFFER", raising=False)
    trace.reset()
    metrics.reset()
    SolverStatistics().reset()
    yield
    trace.reset()
    metrics.reset()
    SolverStatistics().reset()


# -- tracer: disabled fast path ------------------------------------------------------


def test_disabled_span_is_one_shared_null_object():
    """The disabled-mode contract: no event, no timestamp, no per-call
    allocation — every call site gets the SAME null span."""
    assert not trace.enabled()
    assert trace.span("a") is trace.span("b", attr=1)
    with trace.span("c") as sp:
        assert sp.set(x=1) is sp  # .set is a chainable no-op


def test_disabled_decorator_and_instant_are_noops():
    calls = []

    @trace.traced("never.recorded")
    def work(x):
        calls.append(x)
        return x * 2

    assert work(21) == 42
    trace.instant("never.recorded")
    assert calls == [21]
    assert trace.export() is None  # disabled export: no path, no file


def test_decorator_sees_tracer_enabled_after_definition(tmp_path):
    """The enabled check is per CALL: functions decorated at import time
    still record once the tracer turns on later."""

    @trace.traced("late.bind")
    def work():
        return 7

    work()  # disabled: nothing recorded
    out = str(tmp_path / "late.json")
    trace.enable(out)
    work()
    doc = json.load(open(trace.export()))
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert names == ["late.bind"]


# -- tracer: recording + export ------------------------------------------------------


def test_export_is_valid_perfetto_trace_event_json(tmp_path):
    out = str(tmp_path / "trace.json")
    trace.enable(out)
    with trace.span("svm.tx", index=0):
        with trace.span("dispatch.flush", occupancy=4) as flush:
            flush.set(decided=3)
    trace.instant("resilience.breaker_trip", backend="device")
    trace.set_manifest(backend="cpu", argv="pytest")
    assert trace.export() == out

    doc = json.load(open(out))
    assert set(doc) == {"traceEvents", "displayTimeUnit", "otherData"}
    events = doc["traceEvents"]
    # process/thread metadata present
    meta = [e for e in events if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in meta}
    # X events carry numeric ts/dur in us, a cat, and pid/tid
    spans = [e for e in events if e["ph"] == "X"]
    assert [e["name"] for e in spans] == ["dispatch.flush", "svm.tx"]
    for event in spans:
        assert isinstance(event["ts"], (int, float))
        assert isinstance(event["dur"], (int, float))
        assert event["cat"] == event["name"].split(".", 1)[0]
        assert "pid" in event and "tid" in event
    flush_event, tx_event = spans
    assert flush_event["args"] == {"occupancy": 4, "decided": 3}
    # nesting: the inner span lies within the outer one
    assert tx_event["ts"] <= flush_event["ts"]
    assert flush_event["ts"] + flush_event["dur"] \
        <= tx_event["ts"] + tx_event["dur"] + 1e-3
    # instants are thread-scoped
    instants = [e for e in events if e["ph"] == "i"]
    assert len(instants) == 1
    assert instants[0]["s"] == "t"
    assert instants[0]["args"]["backend"] == "device"
    # manifest + accounting
    assert doc["otherData"]["backend"] == "cpu"
    assert doc["otherData"]["dropped_events"] == 0
    assert doc["otherData"]["total_events"] == 3


def test_counter_track_emission(tmp_path):
    """'C' counter samples: each kwarg is one series on the named track —
    ts + args only (no dur, no instant scope), the shape Perfetto renders
    as counter tracks and tools/frontierview.py sums per chunk."""
    out = str(tmp_path / "counters.json")
    trace.enable(out)
    trace.counter("frontier.lanes", running=14, stack=2, escaped=0)
    trace.counter("frontier.lanes", running=9, stack=5, escaped=4)
    trace.counter("frontier.arena", nodes=12)
    doc = json.load(open(trace.export()))
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert [e["name"] for e in counters] == [
        "frontier.lanes", "frontier.lanes", "frontier.arena"]
    for event in counters:
        assert isinstance(event["ts"], (int, float))
        assert "dur" not in event and "s" not in event
        assert event["cat"] == "frontier"
    assert counters[0]["args"] == {"running": 14, "stack": 2, "escaped": 0}
    assert counters[1]["args"] == {"running": 9, "stack": 5, "escaped": 4}
    assert counters[2]["args"] == {"nodes": 12}
    # samples on the same track are time-ordered
    assert counters[0]["ts"] <= counters[1]["ts"]


def test_counter_is_noop_when_disabled():
    assert not trace.enabled()
    trace.counter("frontier.lanes", running=1)  # must not raise or record
    assert trace.export() is None


def test_env_knob_enables_tracer_at_first_use(tmp_path, monkeypatch):
    out = str(tmp_path / "env.json")
    monkeypatch.setenv("MYTHRIL_TPU_TRACE", out)
    trace.reset()  # back to never-touched: env re-checked at next use
    with trace.span("svm.tx"):
        pass
    assert trace.enabled()
    assert trace.out_path() == out
    assert trace.export() == out
    assert os.path.exists(out)


def test_ring_buffer_drops_oldest_and_counts_them(tmp_path, monkeypatch):
    monkeypatch.setenv("MYTHRIL_TPU_TRACE_BUFFER", "64")  # clamps to 1024
    out = str(tmp_path / "drop.json")
    trace.enable(out)
    for i in range(1500):
        with trace.span("tiny.span", i=i):
            pass
    doc = json.load(open(trace.export()))
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 1024
    assert doc["otherData"]["total_events"] == 1500
    assert doc["otherData"]["dropped_events"] == 1500 - 1024
    # the oldest events dropped: the survivors are the most recent ones
    assert spans[0]["args"]["i"] == 1500 - 1024


def test_export_overwrites_atomically_and_is_idempotent(tmp_path):
    out = str(tmp_path / "twice.json")
    trace.enable(out)
    with trace.span("a.one"):
        pass
    trace.export()
    with trace.span("a.two"):
        pass
    doc = json.load(open(trace.export()))
    assert [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"] \
        == ["a.one", "a.two"]
    assert not os.path.exists(out + ".tmp")


# -- metrics registry ----------------------------------------------------------------


def test_undeclared_metric_is_loud():
    # dynamic names on purpose: R6 would (rightly) flag these as literals
    undeclared = "solver.not_a_metric"
    with pytest.raises(KeyError):
        metrics.inc(undeclared)
    with pytest.raises(KeyError):
        metrics.set_gauge(undeclared, 1)
    with pytest.raises(KeyError):
        metrics.observe(undeclared, 1.0)
    with pytest.raises(KeyError):
        metrics.value(undeclared)


def test_kind_mismatch_is_loud():
    with pytest.raises(TypeError):
        metrics.inc("dispatch.flush.occupancy")  # histogram, not counter
    with pytest.raises(TypeError):
        metrics.set_gauge("solver.queries", 3)  # counter, not gauge
    with pytest.raises(TypeError):
        metrics.observe("solver.queries", 3)  # counter, not histogram
    with pytest.raises(TypeError):
        metrics.value("dispatch.flush.occupancy")  # histograms have no value


def test_counters_stay_int_until_a_float_lands():
    metrics.inc("solver.queries")
    metrics.inc("solver.queries", 2)
    assert metrics.value("solver.queries") == 3
    assert isinstance(metrics.value("solver.queries"), int)
    metrics.inc("solver.time", 0.25)
    assert metrics.value("solver.time") == 0.25


def test_histogram_labels_and_aggregates():
    metrics.observe("profiler.instruction_us", 10.0, label="ADD")
    metrics.observe("profiler.instruction_us", 30.0, label="ADD")
    metrics.observe("profiler.instruction_us", 5.0, label="SSTORE")
    assert metrics.labels("profiler.instruction_us") == ["ADD", "SSTORE"]
    hist = metrics.histogram("profiler.instruction_us", "ADD")
    assert hist.as_dict() == {"count": 2, "sum": 40.0, "min": 10.0,
                              "max": 30.0, "avg": 20.0,
                              "p50": 10.0, "p95": 30.0, "p99": 30.0}
    assert metrics.histogram("profiler.instruction_us", "MUL") is None


def test_quantile_nearest_rank_and_edges():
    for value in (10.0, 20.0, 30.0, 40.0):
        metrics.observe("dispatch.flush.latency_ms", value)
    hist = metrics.histogram("dispatch.flush.latency_ms")
    assert hist.quantile(0.5) == 20.0   # ceil(0.5*4)=2 -> 2nd smallest
    assert hist.quantile(0.75) == 30.0
    assert hist.quantile(0.95) == 40.0
    assert hist.quantile(0.0) == 10.0   # q<=0 -> reservoir min
    assert hist.quantile(1.0) == 40.0   # q>=1 -> reservoir max
    assert metrics.quantile("dispatch.flush.latency_ms", 0.5) == 20.0
    # never-observed histograms read 0.0 — the exporter renders them
    # without blowing up on a fresh process
    assert metrics.quantile("serve.request_ms", 0.99) == 0.0


def test_reservoir_overflow_biases_quantiles_but_accounts_drops():
    """Past RESERVOIR observations the quantiles cover only the most
    recent window; `dropped` says exactly how many fell out, and the
    exact aggregates (count/sum/min/max) are unaffected."""
    extra = 1000
    total = metrics.RESERVOIR + extra
    for i in range(total):
        metrics.observe("serve.request_ms", float(i))
    hist = metrics.histogram("serve.request_ms")
    assert hist.count == total
    assert hist.dropped == extra
    assert hist.min == 0.0 and hist.max == float(total - 1)
    # the oldest `extra` observations are gone: the reservoir floor is
    # the first value that survived, not the lifetime minimum
    assert hist.quantile(0.0) == float(extra)
    assert hist.quantile(1.0) == float(total - 1)
    stats = hist.as_dict()
    assert stats["reservoir_dropped"] == extra
    assert stats["count"] == total and stats["min"] == 0.0
    assert stats["p50"] >= float(extra)
    # under-capacity histograms must NOT carry the drop marker
    metrics.observe("dispatch.flush.latency_ms", 1.0)
    small = metrics.histogram("dispatch.flush.latency_ms").as_dict()
    assert "reservoir_dropped" not in small


def test_snapshot_quantiles_roundtrip_frontierview(tmp_path):
    """snapshot() -> write_snapshot -> frontierview --metrics keeps the
    quantile keys end to end: the offline view renders the p95 computed
    by the live reservoir."""
    from tools import frontierview

    for value in (1.0, 2.0, 30.0):
        metrics.observe("frontier.telemetry.op_class", value, label="ADD")
    path = metrics.write_snapshot(str(tmp_path / "metrics.json"))
    snapshot = json.load(open(path))
    assert snapshot["frontier.telemetry.op_class"]["ADD"]["p95"] == 30.0
    report = frontierview.metrics_report(snapshot)
    assert "p95 30.0" in report


def test_snapshot_shape_and_prefix_reset():
    metrics.inc("dispatch.flushes")
    metrics.observe("dispatch.flush.occupancy", 8)
    metrics.inc("frontier.chunks", 5)
    snap = metrics.snapshot()
    assert snap["dispatch.flushes"] == 1
    assert snap["dispatch.flush.occupancy"]["count"] == 1
    assert snap["frontier.chunks"] == 5
    metrics.reset("dispatch.")
    assert metrics.value("dispatch.flushes") == 0
    assert metrics.histogram("dispatch.flush.occupancy") is None
    assert metrics.value("frontier.chunks") == 5  # other prefixes untouched


def test_write_snapshot_is_atomic_json(tmp_path):
    """write_snapshot: valid JSON of the full snapshot, written via a
    temp file + os.replace so a crashed writer never leaves a torn
    file at the destination path."""
    metrics.inc("frontier.telemetry.executed", 122)
    metrics.set_gauge("frontier.telemetry.occupancy", 4.5)
    metrics.observe("frontier.telemetry.op_class", 44, label="push")
    path = str(tmp_path / "metrics.json")
    metrics.write_snapshot(path)
    snap = json.load(open(path))
    assert snap["frontier.telemetry.executed"] == 122
    assert snap["frontier.telemetry.occupancy"] == 4.5
    assert snap["frontier.telemetry.op_class"]["push"]["sum"] == 44
    assert not os.path.exists(path + ".tmp")  # replaced, not left behind


def test_every_facade_field_is_declared():
    for metric_name in FACADE_METRICS.values():
        assert metrics.declared(metric_name), metric_name
    assert metrics.render_markdown_table().startswith("| Metric |")


# -- SolverStatistics facade back-compat ---------------------------------------------


def test_facade_fields_mirror_the_metric_store():
    stats = SolverStatistics()
    stats.query_count += 2
    stats.device_queries += 1
    assert metrics.value("solver.queries") == 2
    assert metrics.value("solver.device.queries") == 1
    metrics.inc("solver.queries", 3)  # writes on either side are one number
    assert stats.query_count == 5
    assert isinstance(stats.query_count, int)


def test_facade_reset_zeroes_scalars_and_reinits_containers():
    stats = SolverStatistics()
    stats.batch_submitted += 7
    stats.failure_counts["device:device_oom"] = 2
    stats.backends_quarantined.append("device")
    stats.batch_bucket_shapes.add((8, 256, 4))
    stats.reset()
    assert stats.batch_submitted == 0
    assert stats.failure_counts == {}
    assert stats.backends_quarantined == []
    assert stats.batch_bucket_shapes == set()


def test_stat_smt_query_decorator_counts_and_times():
    stats = SolverStatistics()

    @stat_smt_query
    def check():
        return "sat"

    assert check() == "sat"
    assert check() == "sat"
    assert stats.query_count == 2
    assert stats.solver_time >= 0.0


def test_batch_metrics_and_repr_preserve_legacy_shapes():
    stats = SolverStatistics()
    stats.batch_submitted += 12
    stats.batch_cache_hits += 3
    stats.batch_flushes += 2
    stats.batch_flushed_queries += 9
    stats.batch_bucket_shapes.add((8, 256, 8))
    batch = stats.batch_metrics()
    assert batch["submitted"] == 12
    assert batch["occupancy"] == 4.5
    assert batch["cache_hit_rate"] == 0.25
    assert batch["buckets_compiled"] == 1
    stats.query_count += 2
    text = repr(stats)
    assert "query count: 2," in text  # ints print as ints, not 2.0
    assert "12 submitted" in text


# -- end to end: a real host-engine run exports a loadable trace ---------------------


def test_host_engine_run_exports_covering_trace(tmp_path):
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import assemble, creation_wrapper

    import tools.traceview as traceview

    out = str(tmp_path / "run.json")
    trace.enable(out)
    creation = creation_wrapper(assemble(
        "PUSH1 0x00\nCALLDATALOAD\nPUSH1 0x2a\nEQ\nPUSH @yes\nJUMPI\nSTOP\n"
        "yes:\nJUMPDEST\nPUSH1 0x01\nPUSH1 0x00\nSSTORE\nSTOP"))
    SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=64,
        execution_timeout=30, create_timeout=15, transaction_count=1,
        compulsory_statespace=False, run_analysis_modules=False)
    path = trace.export()

    events, other = traceview.load_trace(path)
    spans = [e for e in events if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert "svm.create_tx" in names
    assert "svm.tx" in names
    # the engine-phase spans cover (>= 90%) of the traced wall window
    covered, wall = traceview.merged_coverage(spans)
    assert wall > 0
    assert covered / wall >= 0.9, f"span coverage {covered / wall:.1%}"
    # and the report renders a rollup over them
    text = traceview.report(events, other)
    assert "== per-phase wall time ==" in text
    assert "svm.tx" in text
