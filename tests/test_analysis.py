"""End-to-end detector tests (test-strategy parity: reference
tests/integration_tests/analysis_tests.py — positive AND negative contracts,
exact SWC ids, witness validity)."""

import pytest

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontends.asm import (assemble, creation_wrapper, dispatcher,
                                       selector)


def analyze(runtime_src: str, modules=None, tx_count=2, strategy="bfs"):
    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(runtime_src))
                                if isinstance(runtime_src, dict)
                                else assemble(runtime_src))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy=strategy, max_depth=128,
        execution_timeout=60, create_timeout=20, transaction_count=tx_count,
        modules=modules, compulsory_statespace=False)
    return fire_lasers(wrapper, white_list=modules)


KILLBILLY = {
    "activatekillability()": "PUSH1 0x01\nPUSH1 0x00\nSSTORE\nSTOP",
    "commencekilling()":
        "PUSH1 0x00\nSLOAD\nPUSH1 0x01\nEQ\nPUSH @do_kill\nJUMPI\nSTOP\n"
        "do_kill:\nJUMPDEST\nCALLER\nSELFDESTRUCT",
}

SAFE_KILL = {
    # only the creator (stored at deploy time) may kill; slot 0 never settable
    "kill()":
        "CALLER\nPUSH1 0x07\nSLOAD\nEQ\nPUSH @do_kill\nJUMPI\nSTOP\n"
        "do_kill:\nJUMPDEST\nCALLER\nSELFDESTRUCT",
}


def test_unprotected_selfdestruct_found():
    issues = analyze(KILLBILLY, modules=["AccidentallyKillable"], tx_count=2)
    assert len(issues) == 1
    issue = issues[0]
    assert issue.swc_id == "106"
    assert issue.title == "Unprotected Selfdestruct"
    steps = issue.transaction_sequence["steps"]
    assert len(steps) == 3  # creation + activate + kill
    assert steps[1]["input"].startswith(
        "0x%08x" % selector("activatekillability()"))
    assert steps[2]["input"].startswith("0x%08x" % selector("commencekilling()"))


def test_protected_selfdestruct_not_found():
    # storage slot 7 is 0; caller would need to be address 0 which isn't an actor
    issues = analyze(SAFE_KILL, modules=["AccidentallyKillable"], tx_count=2)
    assert issues == []


def test_tx_origin():
    contract = {
        "check()": "ORIGIN\nPUSH1 0x42\nEQ\nPUSH @ok\nJUMPI\nSTOP\n"
                   "ok:\nJUMPDEST\nSTOP",
    }
    issues = analyze(contract, modules=["TxOrigin"], tx_count=1)
    assert len(issues) == 1
    assert issues[0].swc_id == "115"


def test_exception_state():
    contract = {
        "boom()": "PUSH1 0x00\nCALLDATALOAD" + "\nINVALID",
    }
    # dispatcher pops selector then body: INVALID reachable for any calldata
    contract = {"boom()": "INVALID"}
    issues = analyze(contract, modules=["Exceptions"], tx_count=1)
    assert len(issues) == 1
    assert issues[0].swc_id == "110"


def test_ether_thief():
    # anyone can withdraw the contract's whole balance
    contract = {
        "withdraw()":
            # call(gas, caller, selfbalance, 0, 0, 0, 0)
            "PUSH1 0x00\nPUSH1 0x00\nPUSH1 0x00\nPUSH1 0x00\n"
            "SELFBALANCE\nCALLER\nPUSH2 0xffff\nCALL\nPOP\nSTOP",
    }
    issues = analyze(contract, modules=["EtherThief"], tx_count=2)
    assert any(issue.swc_id == "105" for issue in issues)


def test_unchecked_retval():
    contract = {
        "send()":
            "PUSH1 0x00\nPUSH1 0x00\nPUSH1 0x00\nPUSH1 0x00\nPUSH1 0x00\n"
            "CALLER\nPUSH2 0xffff\nCALL\nPOP\nSTOP",
    }
    issues = analyze(contract, modules=["UncheckedRetval"], tx_count=1)
    assert any(issue.swc_id == "104" for issue in issues)


def test_delegatecall_to_calldata_address():
    contract = {
        "exec(address)":
            "PUSH1 0x04\nCALLDATALOAD\n"  # attacker-controlled address
            "PUSH1 0x00\nPUSH1 0x00\nPUSH1 0x00\nPUSH1 0x00\n"
            "DUP5\nGAS\nDELEGATECALL\nPOP\nPOP\nSTOP",
    }
    issues = analyze(contract, modules=["ArbitraryDelegateCall"], tx_count=1)
    assert any(issue.swc_id == "112" for issue in issues)


def test_integer_overflow():
    contract = {
        # balance-like pattern: storage[0] += calldata word, stored unchecked
        "add(uint256)":
            "PUSH1 0x00\nSLOAD\nPUSH1 0x04\nCALLDATALOAD\nADD\n"
            "PUSH1 0x00\nSSTORE\nSTOP",
    }
    # two transactions: the first seeds storage[0] with an attacker value, the
    # second overflows it (a fresh slot is concretely 0, so one tx cannot)
    issues = analyze(contract, modules=["IntegerArithmetics"], tx_count=2)
    assert any(issue.swc_id == "101" for issue in issues)
