"""Multi-device sharded-fleet smoke for the pre-merge gate (MULTICHIP_r06).

Forces a 2-virtual-CPU-device mesh (``jax_num_cpu_devices``, the same
override __graft_entry__.dryrun_multichip uses) and exercises the two
device-resident pieces of the mesh-sharded fleet frontier:

1. **One sharded fleet step**: a fused symbolic chunk driven by a
   2-shard scheduler (vector tops, segmented pools) — must run, keep
   its per-shard counters finite, and leave the lane batch's status
   multiset identical to the same chunk under the legacy scalar
   scheduler (fresh empty pools on both sides, so only the pool
   LAYOUT differs);
2. **One steal exchange**: a forced imbalance (all pending rows in one
   segment) across pool rows that are physically sharded over the two
   devices — the steal pass must move rows through the packed wire
   format bit-identically, conserve the row total, and raise Jain
   fairness.

xfail-style skips (exit 0 with a reason) on a CPU singleton — a jax
build without the ``jax_num_cpu_devices`` config or a mesh that cannot
reach 2 devices — mirroring tests/test_multichip.py's gating.

Prints ``SHARD_SMOKE=ok`` on success; any failure exits non-zero.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("MYTHRIL_TPU_LANES", "8")
os.environ["JAX_PLATFORMS"] = "cpu"
# virtual-device fallback for jax builds without the jax_num_cpu_devices
# config option — must land in the environment before jax initializes a
# backend, hence module scope ahead of any jax import
_FLAGS = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _FLAGS:
    os.environ["XLA_FLAGS"] = (
        _FLAGS + " --xla_force_host_platform_device_count=2").strip()

N_DEVICES = 2


def _skip(reason: str) -> int:
    print(f"shard_smoke: skipped — {reason}")
    print("SHARD_SMOKE=skip")
    return 0


def main() -> int:
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", N_DEVICES)
    except Exception:  # allowlisted: legacy jax uses the XLA_FLAGS path
        pass

    import numpy as np

    jnp = jax.numpy
    devices = jax.devices()
    if len(devices) < N_DEVICES:
        return _skip(f"need {N_DEVICES} devices, have {len(devices)}")

    import __graft_entry__ as graft
    from mythril_tpu.parallel import arena as parena
    from mythril_tpu.parallel import frontier, symstep

    # ---- 1. one sharded fleet step vs the legacy scalar scheduler ----------
    n_lanes = int(os.environ["MYTHRIL_TPU_LANES"])
    state, planes = graft._symbolic_batch(n_lanes)
    arena = parena.new_arena(capacity=1 << 12, const_capacity=1 << 8)
    sched = symstep.new_scheduler(state, planes, 2 * n_lanes, 2 * n_lanes,
                                  n_shards=N_DEVICES)
    sh_state, _, _, sh_sched = symstep.run_chunk(state, planes, arena,
                                                 sched, 8)
    jax.block_until_ready(sh_state.pc)
    if sh_sched.stack_top.shape != (N_DEVICES,):
        print(f"shard_smoke: sharded tops lost their shape: "
              f"{sh_sched.stack_top.shape}", file=sys.stderr)
        return 1

    legacy = symstep.new_scheduler(state, planes, 2 * n_lanes, 2 * n_lanes)
    ref_state, _, _, ref_sched = symstep.run_chunk(state, planes, arena,
                                                   legacy, 8)
    if int(sh_sched.executed) != int(ref_sched.executed):
        print(f"shard_smoke: executed-step divergence: sharded "
              f"{int(sh_sched.executed)} vs legacy {int(ref_sched.executed)}",
              file=sys.stderr)
        return 1
    if sorted(np.asarray(sh_state.status).tolist()) \
            != sorted(np.asarray(ref_state.status).tolist()):
        print("shard_smoke: lane status multiset diverged between the "
              "sharded and legacy schedulers", file=sys.stderr)
        return 1

    # ---- 2. one steal exchange across device-resident pool segments --------
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    pool_rows = 2 * n_lanes
    seg = pool_rows // N_DEVICES
    sched = symstep.new_scheduler(state, planes, pool_rows, pool_rows,
                                  n_shards=N_DEVICES)
    # recognizable pending rows, all parked in shard 1's segment
    filled_state = jax.tree_util.tree_map(
        lambda leaf: jnp.arange(int(np.prod(leaf.shape)), dtype=jnp.int64)
        .reshape(leaf.shape).astype(leaf.dtype)
        if leaf.dtype != jnp.bool_ else
        (jnp.arange(int(np.prod(leaf.shape))).reshape(leaf.shape) % 2 == 0),
        sched.stack_state)
    sched = sched._replace(
        stack_state=filled_state,
        stack_top=jnp.asarray([0, seg], dtype=jnp.int32))

    mesh = Mesh(np.array(devices[:N_DEVICES]), ("dev",))
    row_sharding = NamedSharding(mesh, P("dev"))

    def shard_rows(pytree):
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, row_sharding)
            if getattr(leaf, "ndim", 0) >= 1
            and leaf.shape[:1] == (pool_rows,) else leaf, pytree)

    sched = sched._replace(stack_state=shard_rows(sched.stack_state),
                           stack_planes=shard_rows(sched.stack_planes))
    out = frontier._steal_compiled()(state, sched, min_imbalance=1,
                                     max_rows=seg)
    tops = np.asarray(out.stack_top)
    if int(tops.sum()) != seg:
        print(f"shard_smoke: steal pass lost rows: tops {tops.tolist()} "
              f"sum != {seg}", file=sys.stderr)
        return 1
    moved = int(out.steal_rows)
    if moved < 1 or int(np.asarray(out.steals_received)[0]) != moved:
        print(f"shard_smoke: no rows moved to the starved shard "
              f"(moved={moved}, recv={np.asarray(out.steals_received)})",
              file=sys.stderr)
        return 1
    # the exchanged rows arrived bit-identically (donor top-down order)
    old_pc = np.asarray(filled_state.pc)
    new_pc = np.asarray(out.stack_state.pc)
    for r in range(moved):
        if new_pc[r] != old_pc[pool_rows - 1 - r]:
            print(f"shard_smoke: stolen row {r} corrupted in transit "
                  f"({new_pc[r]} != {old_pc[pool_rows - 1 - r]})",
                  file=sys.stderr)
            return 1

    def jain(load):
        square_sum = float(np.sum(load * load))
        return (float(load.sum()) ** 2 / (len(load) * square_sum)
                if square_sum else 1.0)

    before = np.asarray([0, seg], dtype=np.float64)
    if jain(tops.astype(np.float64)) <= jain(before):
        print(f"shard_smoke: fairness did not rise: {before.tolist()} -> "
              f"{tops.tolist()}", file=sys.stderr)
        return 1

    print(f"shard_smoke: {N_DEVICES}-device mesh — sharded chunk matched "
          f"legacy ({int(sh_sched.executed)} steps), steal exchange moved "
          f"{moved} row(s), tops {tops.tolist()}")
    print("SHARD_SMOKE=ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
