"""Repo tooling package (lint framework, bench/measure scripts)."""
