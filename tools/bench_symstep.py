#!/usr/bin/env python
"""Microbenchmark: fused sym_step_many throughput vs (lanes, chunk) on the
real chip, plus raw tunnel round-trip latency. Picks the frontier's default
batch geometry."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from mythril_tpu.parallel import arena as A
    from mythril_tpu.parallel import batch as pbatch
    from mythril_tpu.parallel import symstep

    print("backend:", jax.devices()[0].platform)

    # tunnel round-trip: dispatch + fetch of a trivial op
    x = jax.device_put(np.zeros(8, dtype=np.int32))
    f = jax.jit(lambda v: v + 1)
    jax.block_until_ready(f(x))
    t0 = time.perf_counter()
    for _ in range(10):
        jax.block_until_ready(f(x))
    print({"tunnel_rt_ms": round((time.perf_counter() - t0) / 10 * 1000, 1)})

    # a loop body with a symbolic compare so planes stay exercised, but no
    # JUMPI fork (lanes run forever): CALLDATALOAD x; PUSH1 1; ADD; POP ...
    code = bytes.fromhex("5b" "600035" "6001" "01" "50" "600056")
    for lanes in (512, 2048):
        specs = [pbatch.LaneSpec(code, gas_limit=2 ** 60)
                 for _ in range(lanes)]
        state = pbatch.build_batch(specs)
        planes = symstep.SymPlanes.empty(
            lanes, state.stack.shape[1], state.memory.shape[1],
            state.storage_keys.shape[1], 64)
        arena = A.new_arena()
        row_bytes = sum(np.asarray(leaf).nbytes
                        for leaf in list(state) + list(planes)) // lanes
        for chunk in (32,):
            s, p, a = symstep.sym_step_many(state, planes, arena, chunk)
            jax.block_until_ready(s.pc)  # compile
            reps = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 3.0:
                s, p, a = symstep.sym_step_many(s, p, a, chunk)
                jax.block_until_ready(s.pc)
                reps += 1
            dt = time.perf_counter() - t0
            rate = reps * chunk * lanes / dt
            print({"lanes": lanes, "chunk": chunk,
                   "lane_steps_per_sec": int(rate),
                   "ms_per_chunk": round(dt / reps * 1000, 1),
                   "row_bytes": int(row_bytes)})


if __name__ == "__main__":
    main()
