"""Gas-superoptimizer smoke for the pre-merge gate (tools/check.sh).

Stdlib + in-repo modules only (no jax import — proofs run on the host
CDCL oracle), so it completes in a couple of seconds:

1. prove the canonical peephole win end to end: a ``PUSH1 0x00; ADD``
   body behind a jump is elided, the rewritten bytecode keeps its exact
   length (relocated STOP + INVALID padding), and the report prices the
   win with the static gas table;
2. prove a strength reduction (``PUSH1 0x08; MUL`` -> ``PUSH1 0x03;
   SHL``) whose miter survives the term-IR constant folder — a *real*
   SAT query — with detection-grade crosscheck at cadence 1: every
   accepted proof re-decided on the host oracle, zero divergences;
3. require the MYTHRIL_TPU_SUPEROPT_CROSSCHECK env knob to drive the
   same cadence through ``support/tpu_config.py``;
4. require byte-for-byte parity between ``superopt/gas.py`` and the
   ``ops/opcodes.py`` schedule (the same contract lint rule R10 and
   tests/test_superopt.py enforce).

Prints ``SUPEROPT_SMOKE=ok`` on success; any failure exits non-zero
with a diagnostic.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the known peephole win: x + 0 == x, body reached with one stack word
ELISION = """
PUSH1 0x00
CALLDATALOAD
PUSH @body
JUMP
body:
JUMPDEST
PUSH1 0x00
ADD
STOP
"""

#: multiply by a constant power of two: the miter does NOT constant-fold
#: (bvmul by 8 survives the term IR), so the proof is a real SAT query
STRENGTH = """
PUSH1 0x00
CALLDATALOAD
PUSH @body
JUMP
body:
JUMPDEST
PUSH1 0x08
MUL
STOP
"""


def _optimize(asm: str, **kwargs):
    from mythril_tpu.frontends.asm import assemble
    from mythril_tpu.superopt import optimize_bytecode

    return optimize_bytecode(assemble(asm).hex(), **kwargs)


def main() -> int:
    # 1) PUSH1 0 ADD elision, end to end into re-emitted bytecode
    report = _optimize(ELISION)
    if len(report.rewrites) != 1:
        print(f"superopt_smoke: elision got {len(report.rewrites)} "
              "rewrites, want 1", file=sys.stderr)
        return 1
    rewrite = report.rewrites[0]
    if tuple(rewrite.before) != ("PUSH1 0x0", "ADD") or rewrite.after:
        print(f"superopt_smoke: elision rewrote {rewrite.before!r} -> "
              f"{rewrite.after!r}, want full elision", file=sys.stderr)
        return 1
    if rewrite.gas_saved != 6 or report.gas_saved != 6:
        print(f"superopt_smoke: elision saved {report.gas_saved} gas, "
              "want 6 (PUSH1 3 + ADD 3)", file=sys.stderr)
        return 1
    if len(report.code_out) != len(report.code_in):
        print("superopt_smoke: elision changed the code length",
              file=sys.stderr)
        return 1
    # the body region (PUSH1 00 ADD STOP) must become STOP + INVALID pad
    if not report.code_out.endswith("5b00fefefe"):
        print(f"superopt_smoke: elision emitted ...{report.code_out[-10:]}, "
              "want ...5b00fefefe", file=sys.stderr)
        return 1

    # 2) strength reduction: a real SAT query, crosschecked at cadence 1
    report = _optimize(STRENGTH, crosscheck=1)
    if len(report.rewrites) != 1 or report.rewrites[0].rule != "strength_mul":
        print(f"superopt_smoke: strength reduction not applied: "
              f"{[r.rule for r in report.rewrites]!r}", file=sys.stderr)
        return 1
    stats = report.proof_stats
    if stats["queries"] < 1 or stats["unsat"] < 1:
        print(f"superopt_smoke: expected a real UNSAT query, got "
              f"{stats!r}", file=sys.stderr)
        return 1
    if stats["crosschecks"] < 1:
        print(f"superopt_smoke: crosscheck cadence 1 ran "
              f"{stats['crosschecks']} crosschecks, want >= 1",
              file=sys.stderr)
        return 1
    if stats["divergences"] != 0 or stats["selfcheck_failures"] != 0:
        print(f"superopt_smoke: divergences/selfcheck failures in "
              f"{stats!r}", file=sys.stderr)
        return 1
    if not report.rewrites[0].after == ("PUSH1 0x3", "SHL"):
        print(f"superopt_smoke: strength reduction emitted "
              f"{report.rewrites[0].after!r}, want PUSH1 0x3; SHL",
              file=sys.stderr)
        return 1

    # 3) the env knob drives the crosscheck cadence via tpu_config
    old = os.environ.get("MYTHRIL_TPU_SUPEROPT_CROSSCHECK")
    os.environ["MYTHRIL_TPU_SUPEROPT_CROSSCHECK"] = "1"
    try:
        report = _optimize(STRENGTH)
        if report.proof_stats["crosschecks"] < 1:
            print("superopt_smoke: MYTHRIL_TPU_SUPEROPT_CROSSCHECK=1 "
                  "did not enable crosschecking", file=sys.stderr)
            return 1
    finally:
        if old is None:
            os.environ.pop("MYTHRIL_TPU_SUPEROPT_CROSSCHECK", None)
        else:
            os.environ["MYTHRIL_TPU_SUPEROPT_CROSSCHECK"] = old

    # 4) gas-table parity with the interpreter's opcode schedule
    from mythril_tpu.ops.opcodes import GAS, OPCODES
    from mythril_tpu.superopt.gas import parity_errors
    errors = parity_errors(OPCODES, GAS)
    if errors:
        print(f"superopt_smoke: gas table drift: {errors[:3]!r}",
              file=sys.stderr)
        return 1

    print("SUPEROPT_SMOKE=ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
