"""loadgen — open-loop load/SLO harness against a real serve daemon.

Replays a mainnet-shaped request mix at a configured arrival rate and
reports per-priority latency quantiles, shed rates, result-store hit
rate, and the autoscaler's pool trajectory:

    python -m tools.loadgen --duration 60 --rate 4
    python -m tools.loadgen --bulk-frac 0.8 --workers-max 3
    python -m tools.loadgen --inject-fault worker_segv:5   # chaos variant

Shape of the load (the mainnet argument, PAPERS.md/DTVM: deployed
bytecode is heavily duplicated, interactive traffic rides on top of
batch sweeps):

* **duplicate-heavy** — requests draw from a small distinct-contract
  corpus with a skewed (zipf-ish) popularity curve, so repeat codehashes
  dominate exactly as they do on-chain and the content-addressed result
  store gets a realistic hit profile;
* **mixed priority** — a configurable fraction rides as ``bulk`` (the
  sweep), the rest as ``interactive`` (the user waiting on a reply);
* **open loop** — arrivals are scheduled from the clock, not from
  completions, so a slow daemon faces a growing queue instead of a
  politely self-throttling client (closed-loop load hides overload).

The daemon is spawned fresh (its own socket/manifest in a temp workdir)
unless ``--socket`` points at one already running. A sampler thread
polls the ``status`` op for queue-depth/pool/autoscaler trajectory.

Output protocol (the bench.py convention): progress as ``{"phase":...}``
JSON lines on stderr, exactly one summary JSON object on stdout. Exit 0
when the run completed (SLO *reporting* is this tool's job; SLO
*gating* is tools/load_smoke.py's).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from mythril_tpu.serve import client as serve_client  # noqa: E402


def _phase(name, **payload):
    print(json.dumps({"phase": name, **payload}), file=sys.stderr,
          flush=True)


# -- workload ------------------------------------------------------------------------


def build_corpus(n_contracts: int) -> List[str]:
    """`n_contracts` distinct tiny-but-real bytecodes: a PUSH1 pad makes
    each one unique, the shared suffix stores a calldata word — a few
    host-engine states each, so service time is dominated by dispatch
    (the thing under test), not symbolic execution."""
    suffix = "600035600055600160005260206000f3"  # calldataload;sstore;return
    return [f"60{i:02x}50{suffix}" for i in range(max(1, n_contracts))]


def pick_contract(corpus: List[str], rng: random.Random) -> str:
    """Zipf-ish popularity: rank r drawn with weight 1/(r+1) — the head
    of the corpus absorbs most of the traffic, like mainnet codehashes."""
    weights = [1.0 / (rank + 1) for rank in range(len(corpus))]
    return rng.choices(corpus, weights=weights, k=1)[0]


def arrival_times(duration_s: float, rate_hz: float,
                  rng: random.Random) -> List[float]:
    """Poisson arrivals (exponential gaps) over the run window —
    open-loop: the schedule exists before the first reply."""
    times, now = [], 0.0
    while now < duration_s:
        now += rng.expovariate(max(rate_hz, 1e-9))
        if now < duration_s:
            times.append(now)
    return times


# -- daemon lifecycle ----------------------------------------------------------------


class SpawnedDaemon:
    """A fresh daemon in a private workdir (socket + manifest + slog)."""

    def __init__(self, args):
        self.workdir = tempfile.mkdtemp(prefix="loadgen_")
        self.socket_path = os.path.join(self.workdir, "serve.sock")
        self.manifest_path = os.path.join(self.workdir, "warmset.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MYTHRIL_TPU_SLOG=os.path.join(self.workdir, "serve.slog"))
        if args.queue_max:
            env["MYTHRIL_TPU_SERVE_QUEUE_MAX"] = str(args.queue_max)
        if args.autoscale_interval_ms:
            env["MYTHRIL_TPU_SERVE_AUTOSCALE_INTERVAL_MS"] = \
                str(args.autoscale_interval_ms)
        if args.autoscale_up_after:
            env["MYTHRIL_TPU_SERVE_AUTOSCALE_UP_AFTER"] = \
                str(args.autoscale_up_after)
        cmd = [sys.executable, "-m", "mythril_tpu.interfaces.cli", "serve",
               "--socket", self.socket_path,
               "--manifest", self.manifest_path,
               "--solver", "cdcl", "--engine", "host",
               "--workers", str(args.workers)]
        if args.workers_min:
            cmd += ["--workers-min", str(args.workers_min)]
        if args.workers_max:
            cmd += ["--workers-max", str(args.workers_max)]
        if args.max_inflight:
            cmd += ["--max-inflight", str(args.max_inflight)]
        if args.inject_fault:
            cmd += ["--inject-fault", args.inject_fault]
        self.process = subprocess.Popen(
            cmd, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE)

    def wait_ready(self, timeout_s: float = 180.0) -> None:
        deadline = time.monotonic() + timeout_s
        while not os.path.exists(self.socket_path):
            if self.process.poll() is not None:
                raise RuntimeError(
                    "daemon died before binding:\n"
                    + self.process.stderr.read().decode(errors="replace"))
            if time.monotonic() > deadline:
                raise RuntimeError("daemon socket never appeared")
            time.sleep(0.2)

    def stop(self) -> None:
        try:
            serve_client.request({"op": "shutdown"},
                                 socket_path=self.socket_path, timeout=30)
        except (serve_client.ServeClientError, OSError):
            pass
        try:
            self.process.wait(timeout=60)
        except subprocess.TimeoutExpired:
            self.process.kill()
            self.process.wait(timeout=10)
        shutil.rmtree(self.workdir, ignore_errors=True)


# -- measurement ---------------------------------------------------------------------


def _quantile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    index = min(len(sorted_values) - 1,
                max(0, int(round(q * (len(sorted_values) - 1)))))
    return sorted_values[index]


def summarize_class(records: List[dict]) -> dict:
    """Latency/outcome rollup for one priority class."""
    lat = sorted(r["elapsed_ms"] for r in records if r["outcome"] == "ok")
    outcomes: Dict[str, int] = {}
    for record in records:
        outcomes[record["outcome"]] = outcomes.get(record["outcome"], 0) + 1
    sent = len(records)
    shed = outcomes.get("overloaded", 0)
    return {
        "sent": sent,
        "ok": outcomes.get("ok", 0),
        "cached": sum(1 for r in records if r.get("cached")),
        "shed": shed,
        "shed_rate": round(shed / sent, 4) if sent else 0.0,
        "outcomes": outcomes,
        "p50_ms": round(_quantile(lat, 0.50), 1),
        "p95_ms": round(_quantile(lat, 0.95), 1),
        "p99_ms": round(_quantile(lat, 0.99), 1),
    }


def run_load(socket_path: str, corpus: List[str], schedule: List[float],
             bulk_frac: float, deadline_ms: Optional[int],
             timeout_s: float, rng: random.Random,
             sample_every_s: float = 0.5):
    """Fire the open-loop schedule at the daemon; returns (records,
    trajectory). One thread per in-flight request (arrivals never wait
    on completions), plus a sampler thread recording the ``status`` op's
    queue/pool/autoscaler view twice a second."""
    records: List[dict] = []
    records_lock = threading.Lock()
    trajectory: List[dict] = []
    stop_sampling = threading.Event()
    start = time.monotonic()

    def fire(at_s: float, priority: str, code: str, request_id: str):
        delay = at_s - (time.monotonic() - start)
        if delay > 0:
            time.sleep(delay)
        payload = {"op": "analyze", "id": request_id, "code": code,
                   "priority": priority}
        if priority == "bulk" and deadline_ms:
            payload["deadline_ms"] = deadline_ms
        sent_at = time.monotonic()
        try:
            reply = serve_client.request(payload, socket_path=socket_path,
                                         timeout=timeout_s)
            outcome = ("ok" if reply.get("ok")
                       else (reply.get("error") or {}).get("code",
                                                           "error"))
            cached = bool(reply.get("cached"))
        except serve_client.ServeClientError as error:
            outcome, cached = f"transport:{type(error).__name__}", False
        record = {"at_s": round(at_s, 3), "priority": priority,
                  "outcome": outcome, "cached": cached,
                  "elapsed_ms": (time.monotonic() - sent_at) * 1000.0}
        with records_lock:
            records.append(record)

    def sample():
        while not stop_sampling.wait(sample_every_s):
            try:
                status = serve_client.request(
                    {"op": "status"}, socket_path=socket_path, timeout=30)
            except (serve_client.ServeClientError, OSError):
                continue
            queue = status.get("queue") or {}
            scaler = status.get("autoscaler") or {}
            store = status.get("result_store") or {}
            trajectory.append({
                "t_s": round(time.monotonic() - start, 2),
                "depth": queue.get("depth"),
                "active": queue.get("active"),
                "shed": queue.get("shed"),
                "pool_target": scaler.get("target"),
                "pool_live": scaler.get("current"),
                "pool_busy": scaler.get("busy"),
                "scale_ups": scaler.get("scale_ups"),
                "store_hits": store.get("hits"),
                "store_hit_rate": store.get("hit_rate"),
            })

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()
    threads = []
    for n, at_s in enumerate(schedule):
        priority = "bulk" if rng.random() < bulk_frac else "interactive"
        code = pick_contract(corpus, rng)
        thread = threading.Thread(
            target=fire, args=(at_s, priority, code, f"load-{n}"),
            daemon=True)
        thread.start()
        threads.append(thread)
    for thread in threads:
        thread.join(timeout=timeout_s + 30)
    stop_sampling.set()
    sampler.join(timeout=5)
    return records, trajectory


def summarize(records: List[dict], trajectory: List[dict],
              args) -> dict:
    by_class = {"interactive": [], "bulk": []}
    for record in records:
        by_class[record["priority"]].append(record)
    classes = {name: summarize_class(rows)
               for name, rows in by_class.items()}
    total_cached = sum(c["cached"] for c in classes.values())
    total_ok = sum(c["ok"] for c in classes.values())
    last = trajectory[-1] if trajectory else {}
    peak_pool = max((t.get("pool_live") or 0 for t in trajectory),
                    default=0)
    return {
        "config": {
            "duration_s": args.duration,
            "rate_hz": args.rate,
            "bulk_frac": args.bulk_frac,
            "contracts": args.contracts,
            "workers": args.workers,
            "workers_min": args.workers_min,
            "workers_max": args.workers_max,
            "queue_max": args.queue_max,
            "inject_fault": args.inject_fault,
            "seed": args.seed,
        },
        "classes": classes,
        "cache": {
            "cached_replies": total_cached,
            "hit_rate_of_ok": round(total_cached / total_ok, 4)
            if total_ok else 0.0,
            "store_hits": last.get("store_hits"),
            "store_hit_rate": last.get("store_hit_rate"),
        },
        "autoscale": {
            "scale_ups": last.get("scale_ups"),
            "peak_pool": peak_pool,
            "final_target": last.get("pool_target"),
        },
        "trajectory": trajectory,
    }


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m tools.loadgen",
        description="Open-loop load/SLO harness for the serve daemon.")
    parser.add_argument("--duration", type=float, default=60.0,
                        help="load window in seconds (default 60)")
    parser.add_argument("--rate", type=float, default=4.0,
                        help="mean arrival rate, requests/s (default 4)")
    parser.add_argument("--bulk-frac", type=float, default=0.75,
                        help="fraction of arrivals sent as bulk "
                             "(default 0.75)")
    parser.add_argument("--contracts", type=int, default=6,
                        help="distinct bytecodes in the corpus "
                             "(default 6; the zipf head repeats)")
    parser.add_argument("--deadline-ms", type=int, default=None,
                        help="deadline_ms attached to BULK requests "
                             "(exercises admission triage)")
    parser.add_argument("--timeout", type=float, default=300.0,
                        help="per-request client timeout (default 300)")
    parser.add_argument("--seed", type=int, default=7,
                        help="workload RNG seed (default 7)")
    parser.add_argument("--socket", default=None,
                        help="target an already-running daemon instead "
                             "of spawning one")
    parser.add_argument("--workers", type=int, default=1,
                        help="spawned daemon's initial worker pool")
    parser.add_argument("--workers-min", type=int, default=1)
    parser.add_argument("--workers-max", type=int, default=2)
    parser.add_argument("--max-inflight", type=int, default=None)
    parser.add_argument("--queue-max", type=int, default=8)
    parser.add_argument("--autoscale-interval-ms", type=int, default=300)
    parser.add_argument("--autoscale-up-after", type=int, default=2)
    parser.add_argument("--inject-fault", default=None,
                        help="fault spec forwarded to the spawned daemon "
                             "(e.g. worker_segv:5 — the chaos variant)")
    return parser


def run_profile(args) -> dict:
    """One full load run: spawn (or target) a daemon, fire the
    schedule, return the summary dict. The reusable core behind
    ``main``, tools/load_smoke.py, and the bench SLO phase."""
    rng = random.Random(args.seed)
    corpus = build_corpus(args.contracts)
    schedule = arrival_times(args.duration, args.rate, rng)
    _phase("plan", requests=len(schedule), contracts=len(corpus),
           duration_s=args.duration, rate_hz=args.rate,
           bulk_frac=args.bulk_frac)
    daemon: Optional[SpawnedDaemon] = None
    socket_path = args.socket
    try:
        if socket_path is None:
            daemon = SpawnedDaemon(args)
            socket_path = daemon.socket_path
            daemon.wait_ready()
            _phase("daemon", socket=socket_path,
                   workers=args.workers, workers_max=args.workers_max,
                   inject_fault=args.inject_fault)
        records, trajectory = run_load(
            socket_path, corpus, schedule, args.bulk_frac,
            args.deadline_ms, args.timeout, rng)
        summary = summarize(records, trajectory, args)
    finally:
        if daemon is not None:
            daemon.stop()
    for name, rollup in summary["classes"].items():
        _phase(f"class.{name}", **{k: v for k, v in rollup.items()
                                   if k != "outcomes"})
    _phase("cache", **summary["cache"])
    _phase("autoscale", **summary["autoscale"])
    return summary


def slo_ab(baseline_args: Optional[List[str]] = None,
           contended_args: Optional[List[str]] = None) -> dict:
    """The SLO A/B behind BENCH_r09+ and the load_smoke latency gate:
    an *uncontended* interactive-only baseline run, then a *contended*
    run with bulk demand past capacity, composed into higher-is-better
    SLO series (benchview trends these):

    * ``interactive_p99_ratio`` — baseline p99 / contended p99; 0.5
      means contended p99 is exactly the acceptance bound (2x the
      uncontended baseline);
    * ``interactive_served_frac`` — 1 - interactive shed rate (must
      stay 1.0: shedding falls on bulk);
    * ``cache_hit_rate`` — the result store's hit rate over the
      duplicate-heavy contended mix.
    """
    parser = build_parser()
    baseline = run_profile(parser.parse_args(baseline_args or [
        "--duration", "20", "--rate", "0.6", "--bulk-frac", "0.0",
        "--contracts", "5", "--workers", "1",
        "--workers-min", "1", "--workers-max", "0",
        "--queue-max", "32", "--timeout", "240",
    ]))
    contended = run_profile(parser.parse_args(contended_args or [
        "--duration", "30", "--rate", "4", "--bulk-frac", "0.75",
        "--contracts", "5", "--workers", "1",
        "--workers-min", "1", "--workers-max", "2",
        "--queue-max", "4", "--autoscale-interval-ms", "300",
        "--autoscale-up-after", "2", "--timeout", "240",
    ]))
    base_p99 = baseline["classes"]["interactive"]["p99_ms"]
    load_p99 = contended["classes"]["interactive"]["p99_ms"]
    shed_rate = contended["classes"]["interactive"]["shed_rate"]
    slo = {
        "rate_hz": contended["config"]["rate_hz"],
        "baseline_interactive_p99_ms": base_p99,
        "contended_interactive_p99_ms": load_p99,
        "interactive_p99_ratio": round(base_p99 / max(load_p99, 1e-9), 4),
        "interactive_served_frac": round(1.0 - shed_rate, 4),
        "cache_hit_rate": contended["cache"]["store_hit_rate"] or 0.0,
        "scale_ups": contended["autoscale"]["scale_ups"],
        "bulk_shed": contended["classes"]["bulk"]["shed"],
    }
    _phase("slo", **slo)
    return {"slo": slo, "baseline": baseline, "contended": contended}


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    parser.add_argument("--slo-ab", action="store_true",
                        help="run the uncontended-baseline vs contended "
                             "A/B and emit the composed SLO series "
                             "(ignores the single-run flags)")
    args = parser.parse_args(argv)
    if args.slo_ab:
        print(json.dumps(slo_ab(), sort_keys=True), flush=True)
        return 0
    print(json.dumps(run_profile(args), sort_keys=True), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
