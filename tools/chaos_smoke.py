"""Worker-pool chaos smoke for the pre-merge gate (tools/check.sh).

Process-level fault-injection soak against a live `myth-tpu serve`
daemon running with a supervised worker pool (CPU-only, CDCL-only, one
worker slot, so it stays cheap). Three phases, each its own daemon:

1. **segv** (`--inject-fault worker_segv:2`): three analyze requests
   for the same contract over one connection. The second dispatched job
   carries the injection and its worker genuinely SIGSEGVs; the daemon
   must survive, retry the victim on a fresh worker, and answer it with
   a report byte-identical to the uninjured requests'. /healthz must
   show the restart and the death, the slog must carry the correlated
   death/retry records, and the poison sidecar must quarantine nobody
   (one crash is below the threshold — a healthy contract that met an
   unlucky worker is not poison).
2. **hang** (`--inject-fault worker_hang:1`, 3 s heartbeat): the first
   job's worker goes silent; the supervisor's heartbeat timeout must
   kill it, classify WORKER_HANG, and the retry must answer the
   request.
3. **quarantine** (`--inject-fault worker_segv:1,worker_segv:2`): both
   the first dispatch and its retry die, so the request fails with the
   typed worker error, the contract's bytecode hash lands in the
   quarantine sidecar, and a repeat request is refused with the typed
   ``quarantined`` error before any worker is risked.

Prints ``CHAOS_SMOKE=ok`` on success; any failure exits non-zero with a
diagnostic. The caller bounds the wall clock (check.sh wraps this in
`timeout`)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mini_contract() -> str:
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)

    runtime = assemble(dispatcher({
        "activatekillability()": "PUSH1 0x01\nPUSH1 0x00\nSSTORE\nSTOP",
        "commencekilling()": ("PUSH1 0x00\nSLOAD\nPUSH1 0x01\nEQ\n"
                              "PUSH @do_kill\nJUMPI\nSTOP\n"
                              "do_kill:\nJUMPDEST\nCALLER\nSELFDESTRUCT"),
    }))
    return creation_wrapper(runtime).hex()


class _Phase:
    """One daemon lifecycle: spawn with an injection spec, run the
    request script, collect problems, always reap the daemon."""

    def __init__(self, name: str, inject: str, extra_env=None):
        self.name = name
        self.workdir = tempfile.mkdtemp(prefix=f"chaos_smoke_{name}_")
        self.socket_path = os.path.join(self.workdir, "serve.sock")
        self.manifest_path = os.path.join(self.workdir, "warmset.json")
        self.slog_path = os.path.join(self.workdir, "serve.slog")
        self.sidecar_path = os.path.join(self.workdir,
                                         "warmset.quarantine.json")
        # chaos phases repeat the same bytecode on purpose (to hit the
        # injected fault on redispatch); the result store would answer
        # the repeats from cache and the fault would never fire
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MYTHRIL_TPU_SLOG=self.slog_path,
                   MYTHRIL_TPU_RESULT_STORE="0")
        env.update(extra_env or {})
        self.daemon = subprocess.Popen(
            [sys.executable, "-m", "mythril_tpu.interfaces.cli", "serve",
             "--socket", self.socket_path, "--manifest", self.manifest_path,
             "--solver", "cdcl", "--engine", "host",
             "--workers", "1", "--inject-fault", inject],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        self.problems = []

    def complain(self, message: str) -> None:
        self.problems.append(f"[{self.name}] {message}")

    def wait_for_socket(self, timeout_s: float = 180.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while not os.path.exists(self.socket_path):
            if self.daemon.poll() is not None:
                self.complain(
                    "daemon died before binding:\n"
                    + self.daemon.stderr.read().decode(errors="replace"))
                return False
            if time.monotonic() > deadline:
                self.complain("socket never appeared")
                return False
            time.sleep(0.2)
        return True

    def slog_text(self) -> str:
        try:
            with open(self.slog_path, encoding="utf-8") as handle:
                return handle.read()
        except OSError:
            return ""

    def sidecar(self) -> dict:
        try:
            with open(self.sidecar_path, encoding="utf-8") as handle:
                return json.load(handle)
        except (OSError, ValueError):
            return {}

    def finish(self) -> None:
        try:
            self.daemon.wait(timeout=60)
            if self.daemon.returncode != 0:
                self.complain(
                    f"daemon exited {self.daemon.returncode}:\n"
                    + self.daemon.stderr.read().decode(errors="replace"))
        except subprocess.TimeoutExpired:
            self.complain("daemon did not drain after shutdown")
        finally:
            if self.daemon.poll() is None:
                self.daemon.kill()
                self.daemon.wait(timeout=10)


def _analyze(code: str, rid: str) -> dict:
    return {"op": "analyze", "id": rid, "code": code,
            "transaction_count": 2, "deadline_ms": 120_000}


def _phase_segv(code: str) -> list:
    from mythril_tpu.serve import client

    phase = _Phase("segv", "worker_segv:2")
    try:
        if not phase.wait_for_socket():
            return phase.problems
        replies = client.roundtrip(
            [{"op": "ping", "id": "c-ping"},
             _analyze(code, "c-r1"), _analyze(code, "c-r2"),
             _analyze(code, "c-r3"),
             {"op": "healthz", "id": "c-healthz"},
             {"op": "metrics", "id": "c-metrics"},
             {"op": "shutdown", "id": "c-shutdown"}],
            socket_path=phase.socket_path, timeout=600)
        if not all(reply.get("ok") for reply in replies):
            phase.complain(f"non-ok reply among {replies}")
            return phase.problems
        r1, r2, r3 = replies[1], replies[2], replies[3]
        reports = [json.dumps(r.get("report"), sort_keys=True)
                   for r in (r1, r2, r3)]
        if len(set(reports)) != 1:
            phase.complain("injured request's report is not byte-identical "
                           "to its uninjured peers'")
        if r2.get("issue_count", 0) < 1:
            phase.complain(f"expected >=1 issue from the retried request, "
                           f"got {r2.get('issue_count')}")
        pool = replies[4].get("workers") or {}
        if pool.get("restarts", 0) < 1:
            phase.complain(f"/healthz shows no worker restart: {pool}")
        if pool.get("deaths", 0) < 1:
            phase.complain(f"/healthz shows no worker death: {pool}")
        if pool.get("live", 0) < 1:
            phase.complain(f"/healthz shows no live worker: {pool}")
        if (pool.get("quarantine") or {}).get("quarantined", -1) != 0:
            phase.complain(f"healthy contract was quarantined: {pool}")
        exposition = replies[5].get("exposition", "")
        if "serve_worker_restarts" not in exposition:
            phase.complain("metrics exposition lacks the worker restart "
                           f"counter: {exposition[:400]!r}")
        slog_text = phase.slog_text()
        for marker in ("serve.worker.death", "serve.worker.retry",
                       "worker_segv"):
            if marker not in slog_text:
                phase.complain(f"slog lacks {marker!r}")
        cid = r2.get("correlation_id", "")
        if cid and cid not in slog_text:
            phase.complain(f"injured request's cid {cid!r} absent from slog")
        doc = phase.sidecar()
        quarantined = [key for key, entry in
                       (doc.get("contracts") or {}).items()
                       if entry.get("quarantined")]
        if quarantined:
            phase.complain(f"sidecar quarantined healthy contract(s): "
                           f"{quarantined}")
        phase.finish()
        return phase.problems
    finally:
        if phase.daemon.poll() is None:
            phase.daemon.kill()
            phase.daemon.wait(timeout=10)


def _phase_hang(code: str) -> list:
    from mythril_tpu.serve import client

    phase = _Phase("hang", "worker_hang:1",
                   extra_env={"MYTHRIL_TPU_SERVE_WORKER_HEARTBEAT_MS":
                              "3000"})
    try:
        if not phase.wait_for_socket():
            return phase.problems
        replies = client.roundtrip(
            [_analyze(code, "h-r1"),
             {"op": "healthz", "id": "h-healthz"},
             {"op": "shutdown", "id": "h-shutdown"}],
            socket_path=phase.socket_path, timeout=600)
        if not all(reply.get("ok") for reply in replies):
            phase.complain(f"non-ok reply among {replies}")
            return phase.problems
        if replies[0].get("issue_count", 0) < 1:
            phase.complain("retried request after the hang found no issue")
        pool = replies[1].get("workers") or {}
        if pool.get("deaths", 0) < 1:
            phase.complain(f"/healthz shows no death after the hang: {pool}")
        if "worker_hang" not in phase.slog_text():
            phase.complain("slog lacks the worker_hang classification")
        phase.finish()
        return phase.problems
    finally:
        if phase.daemon.poll() is None:
            phase.daemon.kill()
            phase.daemon.wait(timeout=10)


def _phase_quarantine(code: str) -> list:
    from mythril_tpu.serve import client

    phase = _Phase("quarantine", "worker_segv:1,worker_segv:2")
    try:
        if not phase.wait_for_socket():
            return phase.problems
        replies = client.roundtrip(
            [_analyze(code, "q-r1"), _analyze(code, "q-r2"),
             {"op": "healthz", "id": "q-healthz"},
             {"op": "shutdown", "id": "q-shutdown"}],
            socket_path=phase.socket_path, timeout=600)
        first, second, healthz = replies[0], replies[1], replies[2]
        if first.get("ok"):
            phase.complain(f"double-killed request should fail: {first}")
        elif first.get("error", {}).get("code") != "analysis_failed":
            phase.complain(f"double death reported as "
                           f"{first.get('error')}, want analysis_failed")
        if second.get("ok"):
            phase.complain(f"quarantined contract was served: {second}")
        elif second.get("error", {}).get("code") != "quarantined":
            phase.complain(f"repeat request error is {second.get('error')},"
                           f" want the typed 'quarantined' refusal")
        pool = healthz.get("workers") or {}
        if (pool.get("quarantine") or {}).get("quarantined") != 1:
            phase.complain(f"/healthz quarantine census is not 1: {pool}")
        doc = phase.sidecar()
        entries = doc.get("contracts") or {}
        if not any(entry.get("quarantined") and entry.get("crashes", 0) >= 2
                   for entry in entries.values()):
            phase.complain(f"sidecar lacks the quarantined record: {doc}")
        phase.finish()
        return phase.problems
    finally:
        if phase.daemon.poll() is None:
            phase.daemon.kill()
            phase.daemon.wait(timeout=10)


def main() -> int:
    code = _mini_contract()
    problems = []
    started = time.monotonic()
    for runner in (_phase_segv, _phase_hang, _phase_quarantine):
        problems.extend(runner(code))
    if problems:
        print("chaos_smoke: FAIL\n" + "\n".join(problems), file=sys.stderr)
        return 1
    print(f"CHAOS_SMOKE=ok phases=segv,hang,quarantine "
          f"elapsed_s={time.monotonic() - started:.1f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
