"""Run-report CLI for mythril-tpu Perfetto traces.

    python -m tools.traceview TRACE.json

Reads a Chrome ``trace_event`` JSON written by the observe span tracer
(``MYTHRIL_TPU_TRACE=out.json`` / ``analyze --trace-out``) and prints:

* the run manifest (``otherData``: argv/backend/contract, drop counts);
* per-phase wall-time rollups — spans grouped by category (the leading
  dotted component of the span name: ``dispatch.flush`` -> ``dispatch``)
  and by full name, with count/total/mean/max and percent of the traced
  wall clock;
* span coverage: the fraction of the trace's wall window covered by at
  least one span (merged intervals, per thread, then worst/best) —
  ISSUE 5's acceptance wants >= 90% of measured wall time inside spans;
* device-flush occupancy and latency histograms (``dispatch.flush``
  spans' ``occupancy`` arg + duration), mirroring
  SolverStatistics.batch_metrics;
* XLA compile accounting: every ``xla.compile`` span with its
  clause-shape key and cost — the per-shape compile cliff that the pow2
  bucketing exists to bound;
* gas-superoptimization rollup (``superopt.prove`` spans): obligation/
  query counts, the unsat/sat/unknown proof split, and whether the
  proofs rode the batched device dispatch;
* serve rollup (traces from `myth-tpu serve` only): warmup attributed
  separately from request time, then request id -> duration, warm vs
  cold dispatch counts, and the per-phase breakdown inside each request
  window.

Stdlib-only (json/argparse/math): usable on a workstation without jax.
Exit codes: 0 on success, 2 when the file is missing or not a valid
trace_event document.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

#: bar width for the text histograms
_BAR = 40


def load_trace(path: str) -> Tuple[List[dict], Dict[str, object]]:
    """Parse a trace_event document: the JSON Object Format
    ({"traceEvents": [...], ...}) or the bare JSON Array Format.
    Raises ValueError on anything else."""
    with open(path, "r", encoding="utf-8") as handle:
        doc = json.load(handle)
    if isinstance(doc, list):
        events, other = doc, {}
    elif isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list):
        events, other = doc["traceEvents"], dict(doc.get("otherData") or {})
    else:
        raise ValueError(
            "not a trace_event document: expected a JSON array of events "
            "or an object with a 'traceEvents' list")
    for event in events:
        if not isinstance(event, dict) or "ph" not in event:
            raise ValueError("malformed trace event (no 'ph' field): "
                             f"{event!r:.120}")
    return events, other


def _spans(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("ph") == "X"]


def _fmt_us(us: float) -> str:
    """Adaptive duration: us under 1ms, ms under 1s, else seconds."""
    if us < 1_000:
        return f"{us:.0f}us"
    if us < 1_000_000:
        return f"{us / 1_000:.1f}ms"
    return f"{us / 1_000_000:.2f}s"


def rollup(spans: List[dict], key) -> List[dict]:
    """Aggregate spans by `key(event)`: count/total/mean/max, sorted by
    total descending."""
    groups: Dict[str, List[float]] = defaultdict(list)
    for span in spans:
        groups[key(span)].append(float(span.get("dur", 0.0)))
    out = []
    for name, durs in groups.items():
        out.append({
            "name": name, "count": len(durs), "total_us": sum(durs),
            "mean_us": sum(durs) / len(durs), "max_us": max(durs),
        })
    out.sort(key=lambda row: -row["total_us"])
    return out


def merged_coverage(spans: List[dict]) -> Tuple[float, float]:
    """(covered_us, wall_us): microseconds of the trace window covered by
    at least one span on SOME thread (intervals merged across threads —
    concurrent spans count once), and the window's full width."""
    if not spans:
        return 0.0, 0.0
    intervals = sorted(
        (float(s["ts"]), float(s["ts"]) + float(s.get("dur", 0.0)))
        for s in spans)
    covered = 0.0
    cur_start, cur_end = intervals[0]
    for start, end in intervals[1:]:
        if start > cur_end:
            covered += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    covered += cur_end - cur_start
    wall = max(end for _, end in intervals) - min(
        start for start, _ in intervals)
    return covered, wall


def text_histogram(values: List[float], n_bins: int = 8) -> List[str]:
    """Fixed-width text histogram lines for a value list."""
    if not values:
        return ["  (no observations)"]
    lo, hi = min(values), max(values)
    if hi <= lo:
        return [f"  {lo:10.1f} |{'#' * _BAR}| {len(values)}"]
    width = (hi - lo) / n_bins
    counts = [0] * n_bins
    for value in values:
        slot = min(int((value - lo) / width), n_bins - 1)
        counts[slot] += 1
    peak = max(counts)
    lines = []
    for i, count in enumerate(counts):
        left = lo + i * width
        bar = "#" * max(1 if count else 0,
                        int(round(count / peak * _BAR)))
        lines.append(f"  {left:10.1f} |{bar:<{_BAR}}| {count}")
    return lines


def report(events: List[dict], other: Dict[str, object]) -> str:
    lines: List[str] = []
    spans = _spans(events)
    instants = [e for e in events if e.get("ph") == "i"]

    lines.append("== run manifest ==")
    if other:
        for key, value in sorted(other.items()):
            lines.append(f"  {key}: {value}")
    else:
        lines.append("  (none recorded)")
    lines.append(f"  span events: {len(spans)}, instant events: "
                 f"{len(instants)}")

    covered, wall = merged_coverage(spans)
    lines.append("")
    lines.append("== per-phase wall time ==")
    if not spans:
        lines.append("  (no spans)")
    else:
        lines.append(f"  traced wall window: {_fmt_us(wall)}, span "
                     f"coverage: {covered / wall * 100 if wall else 0:.1f}%")
        for row in rollup(spans, lambda s: s.get("cat")
                          or s["name"].split(".", 1)[0]):
            share = row["total_us"] / wall * 100 if wall else 0.0
            lines.append(
                f"  [{share:5.1f}%] {row['name']:<12} "
                f"total {_fmt_us(row['total_us']):>9}  "
                f"x{row['count']:<6} mean {_fmt_us(row['mean_us']):>9}  "
                f"max {_fmt_us(row['max_us']):>9}")
        lines.append("")
        lines.append("== per-span rollup ==")
        for row in rollup(spans, lambda s: s["name"]):
            share = row["total_us"] / wall * 100 if wall else 0.0
            lines.append(
                f"  [{share:5.1f}%] {row['name']:<26} "
                f"total {_fmt_us(row['total_us']):>9}  "
                f"x{row['count']:<6} mean {_fmt_us(row['mean_us']):>9}  "
                f"max {_fmt_us(row['max_us']):>9}")

    flushes = [s for s in spans if s["name"] == "dispatch.flush"]
    lines.append("")
    lines.append("== device flush (dispatch.flush) ==")
    if flushes:
        occupancies = [float(s.get("args", {}).get("occupancy", 0))
                       for s in flushes]
        lines.append(f"  flushes: {len(flushes)}, queries: "
                     f"{sum(occupancies):.0f}, mean occupancy: "
                     f"{sum(occupancies) / len(occupancies):.2f}/flush")
        lines.append("  occupancy (queries/flush):")
        lines.extend(text_histogram(occupancies))
        lines.append("  latency (ms/flush):")
        lines.extend(text_histogram(
            [float(s.get("dur", 0.0)) / 1_000 for s in flushes]))
    else:
        lines.append("  (no flushes recorded)")

    compiles = [s for s in spans if s["name"] == "xla.compile"]
    lines.append("")
    lines.append("== XLA compiles (per clause-shape bucket) ==")
    if compiles:
        total = sum(float(s.get("dur", 0.0)) for s in compiles)
        lines.append(f"  {len(compiles)} first-call bucket(s), "
                     f"{_fmt_us(total)} total compile-or-cache-load")
        for span in sorted(compiles, key=lambda s: -float(s.get("dur", 0))):
            shape = span.get("args", {}).get("shape", "?")
            lines.append(f"  {_fmt_us(float(span.get('dur', 0.0))):>9}  "
                         f"{shape}")
    else:
        lines.append("  (no xla.compile spans — every bucket was warm)")

    lines.extend(_staticanalysis_section(spans))
    lines.extend(_superopt_section(spans))
    lines.extend(_serve_section(spans, instants))

    if instants:
        lines.append("")
        lines.append("== instant events ==")
        for event in instants:
            args = event.get("args") or {}
            detail = ", ".join(f"{k}={v}" for k, v in sorted(args.items()))
            lines.append(f"  @{_fmt_us(float(event.get('ts', 0.0))):>9}  "
                         f"{event['name']}" + (f"  ({detail})" if detail
                                               else ""))
    return "\n".join(lines)


def _staticanalysis_section(spans: List[dict]) -> List[str]:
    """Per-contract static-analysis builds: one line per ``cfa.build`` /
    ``taint.build`` span with the table sizes it produced (or ``bailed``
    when the pass gave up). Empty (section omitted) for traces without
    those spans, so existing reports are unchanged."""
    builds = [s for s in spans if s["name"] in ("cfa.build", "taint.build")]
    if not builds:
        return []
    lines = ["", "== static analysis (per-contract builds) =="]
    for span in sorted(builds, key=lambda s: float(s.get("ts", 0.0))):
        args = span.get("args", {})
        detail = ", ".join(f"{k}={v}" for k, v in sorted(args.items()))
        lines.append(f"  {span['name']:<12} {_fmt_us(float(span.get('dur', 0.0))):>9}"
                     + (f"  ({detail})" if detail else ""))
    return lines


def _superopt_section(spans: List[dict]) -> List[str]:
    """Gas-superoptimization rollup: one line per ``superopt.prove``
    span with its obligation/query counts and the proof outcome split
    (unsat = accepted equivalences, sat = distinguishable candidates,
    unknown = ladder exhaustions), plus whether the proofs rode the
    batched device dispatch. Empty (section omitted) for traces without
    superopt spans, so existing reports are unchanged."""
    proofs = [s for s in spans if s["name"] == "superopt.prove"]
    if not proofs:
        return []
    lines = ["", "== gas superoptimization (superopt.prove) =="]
    for span in sorted(proofs, key=lambda s: float(s.get("ts", 0.0))):
        args = span.get("args", {})
        lines.append(
            f"  {_fmt_us(float(span.get('dur', 0.0))):>9}  "
            f"obligations={args.get('obligations', '?')} "
            f"queries={args.get('queries', '?')} "
            f"unsat={args.get('unsat', '?')} sat={args.get('sat', '?')} "
            f"unknown={args.get('unknown', '?')} "
            f"batched={args.get('batched', '?')}")
    return lines


def _serve_section(spans: List[dict],
                   instants: Optional[List[dict]] = None) -> List[str]:
    """Serve-daemon rollup: warmup attributed separately from request
    time, then one line per request (id, duration, warm vs cold dispatch
    counts) with its per-phase breakdown — spans that ran inside the
    request window, grouped by category — and, for worker-pool daemons,
    the worker lifecycle (ready/death/quarantine instants). Empty
    (section omitted) for traces without serve spans, so non-serve
    reports are unchanged."""
    warmups = [s for s in spans if s["name"] == "serve.warmup"]
    requests = [s for s in spans if s["name"] == "serve.request"]
    if not warmups and not requests:
        return []
    lines = ["", "== serve (warmup vs requests) =="]
    lines.extend(_worker_lifecycle(instants or []))
    for span in warmups:
        args = span.get("args", {})
        line = (f"  warmup: {_fmt_us(float(span.get('dur', 0.0)))} — "
                f"{args.get('warmed', '?')}/{args.get('buckets', '?')} "
                f"manifest bucket(s) warmed")
        if args.get("failed"):
            line += f", {args['failed']} unwarmable"
        lines.append(line)
        if "exec_hits" in args or "verdicts_loaded" in args:
            lines.append(
                f"    durable warmth: exec cache "
                f"{args.get('exec_hits', 0)} hit(s) / "
                f"{args.get('exec_misses', 0)} miss(es), "
                f"{args.get('verdicts_loaded', 0)} verdict(s) loaded")
    if not warmups:
        lines.append("  (no warmup span — daemon started with warmup off)")
    if requests:
        durations = sorted(float(s.get("dur", 0.0)) for s in requests)

        def _q(q: float) -> float:
            # nearest-rank, matching metrics._Hist.quantile
            rank = math.ceil(q * len(durations)) - 1
            return durations[max(0, min(rank, len(durations) - 1))]

        lines.append(
            f"  request latency ({len(durations)} request(s)): "
            f"p50 {_fmt_us(_q(0.5))}  p95 {_fmt_us(_q(0.95))}  "
            f"p99 {_fmt_us(_q(0.99))}")
    for request in sorted(requests, key=lambda s: float(s.get("ts", 0.0))):
        args = request.get("args", {})
        start = float(request.get("ts", 0.0))
        dur = float(request.get("dur", 0.0))
        cid = args.get("correlation_id")
        lines.append(
            f"  request {args.get('request_id', '?')}: {_fmt_us(dur)}  "
            f"cold_buckets={args.get('cold_buckets', '?')} "
            f"warm_hits={args.get('warm_hits', '?')} "
            + (f"exec_hits={args['exec_hits']} " if "exec_hits" in args
               else "")
            + f"issues={args.get('issues', '?')}"
            + (f" cid={cid}" if cid else ""))
        inner = [
            s for s in spans
            if s is not request and not s["name"].startswith("serve.")
            and start <= float(s.get("ts", 0.0))
            and (float(s.get("ts", 0.0)) + float(s.get("dur", 0.0))
                 <= start + dur)]
        for row in rollup(inner, lambda s: s.get("cat")
                          or s["name"].split(".", 1)[0]):
            share = row["total_us"] / dur * 100 if dur else 0.0
            lines.append(
                f"    [{share:5.1f}%] {row['name']:<12} "
                f"total {_fmt_us(row['total_us']):>9}  "
                f"x{row['count']:<6} mean {_fmt_us(row['mean_us']):>9}")
    return lines


def _worker_lifecycle(instants: List[dict]) -> List[str]:
    """Worker-pool lifecycle rollup from the supervisor's trace
    instants: spawn/ready count, deaths grouped by failure class, and
    quarantine additions. Empty for daemons without a pool."""
    ready = [e for e in instants if e.get("name") == "serve.worker.ready"]
    deaths = [e for e in instants if e.get("name") == "serve.worker.death"]
    poisoned = [e for e in instants
                if e.get("name") == "serve.quarantine.added"]
    if not ready and not deaths and not poisoned:
        return []
    lines = [f"  worker pool: {len(ready)} ready event(s), "
             f"{len(deaths)} death(s), {len(poisoned)} contract(s) "
             f"quarantined"]
    by_class: Dict[str, int] = defaultdict(int)
    for event in deaths:
        by_class[str((event.get("args") or {}).get("failure_class",
                                                   "?"))] += 1
    for failure_class in sorted(by_class):
        lines.append(f"    death class {failure_class:<14} "
                     f"x{by_class[failure_class]}")
    for event in sorted(deaths, key=lambda e: float(e.get("ts", 0.0))):
        args = event.get("args") or {}
        lines.append(
            f"    @{_fmt_us(float(event.get('ts', 0.0))):>9}  slot "
            f"{args.get('slot', '?')} died: "
            f"{args.get('failure_class', '?')}"
            + (f" ({args.get('detail')})" if args.get("detail") else ""))
    for event in poisoned:
        args = event.get("args") or {}
        lines.append(f"    quarantined contract "
                     f"{args.get('contract', '?')}…")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.traceview",
        description="per-phase wall-time report for a mythril-tpu "
                    "Perfetto trace")
    parser.add_argument("trace", help="trace_event JSON written via "
                        "MYTHRIL_TPU_TRACE / --trace-out / bench.py")
    args = parser.parse_args(argv)
    try:
        events, other = load_trace(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as error:
        print(f"traceview: cannot read {args.trace}: {error}",
              file=sys.stderr)
        return 2
    print(report(events, other))
    return 0


if __name__ == "__main__":
    sys.exit(main())
