#!/usr/bin/env bash
# One-shot pre-merge gate: tpu-lint, the serve smoke, then the tier-1
# suite.
#
#     tools/check.sh            # lint + tier-1 (the ROADMAP "Tier-1 verify")
#     tools/check.sh --lint     # lint only (fast pre-commit)
#
# Exits non-zero on the first failing stage. The tier-1 stage is the
# exact command from ROADMAP.md (870 s budget, slow tests excluded) and
# prints DOTS_PASSED= for the driver.

set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== tpu-lint =="
python -m tools.lint || exit $?

if [ "${1:-}" = "--lint" ]; then
    exit 0
fi

echo
echo "== taint smoke (summaries + module screen on the vendored corpus) =="
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python -m tools.taint_smoke || exit $?

echo
echo "== absint smoke (value ranges + join windows + loop bounds, jax-free) =="
timeout -k 10 120 python -m tools.absint_smoke || exit $?

echo
echo "== superopt smoke (peephole proof + crosscheck + gas parity, jax-free) =="
timeout -k 10 120 python -m tools.superopt_smoke || exit $?

echo
echo "== frontierview smoke (jax-free counter-track report) =="
timeout -k 10 60 python -m tools.frontierview \
    tests/data/trace/frontier_trace.json > /dev/null || exit $?

echo
echo "== merge smoke (state-merge A/B: >=1 merge event + parity) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu \
    python -m tools.merge_smoke || exit $?

echo
echo "== benchview self-check (injected regression must trip the gate) =="
timeout -k 10 60 python -m tools.benchview --self-check || exit $?

echo
echo "== benchview (perf-regression sentinel over BENCH_r*.json) =="
timeout -k 10 60 python -m tools.benchview || exit $?

echo
echo "== fleet smoke (2-contract fleet A/B: shared dispatch flush + parity) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m tools.fleet_smoke || exit $?

echo
echo "== serve smoke (daemon start -> request -> metrics scrape -> clean shutdown) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu \
    python -m tools.serve_smoke || exit $?

echo
echo "== chaos smoke (worker segv/hang injection -> retry -> quarantine) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m tools.chaos_smoke || exit $?

echo
echo "== warm smoke (cold compile+persist -> fresh process respawns warm) =="
timeout -k 10 400 env JAX_PLATFORMS=cpu \
    python -m tools.warm_smoke || exit $?

echo
echo "== load smoke (open-loop overload: 0 interactive shed + autoscale-up + result-store hit) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m tools.load_smoke || exit $?

echo
echo "== multichip r06 (2-device sharded fleet step + steal exchange; skips on singleton) =="
timeout -k 10 300 python -m tools.shard_smoke || exit $?

echo
echo "== tier-1 (pytest, not slow, 870s budget) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
