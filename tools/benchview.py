"""benchview — the perf-regression sentinel over the BENCH lineage.

Reads the committed ``BENCH_r*.json`` history (one file per bench round:
``{"n", "cmd", "rc", "tail", "parsed"}``), extracts every tracked
headline number, renders the trend per metric, and exits non-zero when a
number regressed beyond tolerance between consecutive *comparable* runs:

    python -m tools.benchview                       # repo lineage
    python -m tools.benchview --tolerance 0.1
    python -m tools.benchview --metrics bench_metrics.json
    python -m tools.benchview --self-check          # CI fixture gate

Tracked numbers and their comparability keys:

* the headline throughput (``sym_states_per_sec`` /
  ``lockstep_lane_steps_per_sec``), keyed by (metric, backend,
  n_branches, n_lanes) — a 4096-lane TPU run is never compared against
  a 128-lane CPU run, so heterogeneous history stays green;
* ``merge_ab.wall_speedup`` / ``merge_ab.states_ratio``, keyed by
  (backend, chunk);
* ``fleet_ab.wall_speedup`` / ``fleet_ab.flush_occupancy_ratio``, keyed
  by (backend, contracts) — the fleet-vs-sequential corpus A/B;
* ``superopt_ab.proof_speedup`` / ``superopt_ab.flush_occupancy`` from
  the gas-superoptimizer proof-discharge A/B (``bench.py superopt_ab``),
  keyed by (backend, queries) — batched-device vs sequential-host
  equivalence proving over the same rewrite obligations;
* the ``slo.*`` overload-resilience series from the tools/loadgen.py
  A/B (``interactive_p99_ratio``, ``interactive_served_frac``,
  ``cache_hit_rate``), keyed by (backend, rate_hz) — all fractions
  where bigger means a healthier daemon under the same load;
* the corpus sweep medians and finding totals per engine, keyed by
  (engine, budget_s).

All tracked numbers are higher-is-better. A value that *drops* by more
than ``--tolerance`` (default: the ``MYTHRIL_TPU_BENCH_TOLERANCE`` knob,
0.2) between one run and the next run with the same key is a regression
-> exit 1. Rounds without a parsed payload (timeouts, infra failures)
are reported and skipped, never silently dropped.

``--metrics`` additionally renders the solver-latency quantiles and XLA
compile counts from a fresh ``bench_metrics.json`` snapshot (the file
``bench.py`` writes beside its BENCH output) — display-only context, not
gated, because snapshots are not part of the committed lineage.

``--self-check`` builds a clean fixture lineage (must exit 0) and one
with an injected >=20% throughput regression (must exit 1) in a temp
directory and verifies both verdicts — the CI proof that the gate can
actually fail. Stdlib + tpu_config only: no jax import, safe for any
CI box.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from typing import Dict, List, NamedTuple, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # `python tools/benchview.py` form
    sys.path.insert(0, _REPO)

from mythril_tpu.support import tpu_config  # noqa: E402


class Point(NamedTuple):
    """One tracked number from one bench round."""

    series: str        #: display name, e.g. "sym_states_per_sec"
    key: tuple         #: comparability key (series + run configuration)
    round_label: str   #: "r05"
    value: float
    unit: str


class Regression(NamedTuple):
    series: str
    key: tuple
    prev_label: str
    prev_value: float
    label: str
    value: float
    drop: float        #: fractional drop, e.g. 0.31


def _num(value) -> Optional[float]:
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    return None


def extract_points(round_label: str, run: dict) -> List[Point]:
    """Every tracked number in one BENCH round's parsed payload."""
    parsed = run.get("parsed")
    if not isinstance(parsed, dict):
        return []
    points: List[Point] = []
    metric = parsed.get("metric")
    value = _num(parsed.get("value"))
    if isinstance(metric, str) and value is not None:
        key = (metric, parsed.get("backend"), parsed.get("n_branches"),
               parsed.get("n_lanes"))
        points.append(Point(metric, key, round_label, value,
                            str(parsed.get("unit", ""))))
    merge = parsed.get("merge_ab")
    if isinstance(merge, dict):
        for field in ("wall_speedup", "states_ratio"):
            field_value = _num(merge.get(field))
            if field_value is not None:
                series = f"merge_ab.{field}"
                key = (series, parsed.get("backend"), merge.get("chunk"))
                points.append(Point(series, key, round_label,
                                    field_value, "x"))
    fleet = parsed.get("fleet_ab")
    if isinstance(fleet, dict):
        for field in ("wall_speedup", "flush_occupancy_ratio"):
            field_value = _num(fleet.get(field))
            if field_value is not None:
                series = f"fleet_ab.{field}"
                key = (series, parsed.get("backend"), fleet.get("contracts"))
                points.append(Point(series, key, round_label,
                                    field_value, "x"))
    shard = parsed.get("shard_ab")
    if isinstance(shard, dict):
        for field in ("wall_speedup", "fairness_gain"):
            field_value = _num(shard.get(field))
            if field_value is not None:
                series = f"shard_ab.{field}"
                key = (series, parsed.get("backend"), shard.get("devices"),
                       shard.get("contracts"))
                points.append(Point(series, key, round_label,
                                    field_value, "x"))
    warm = parsed.get("warm_start")
    if isinstance(warm, dict):
        speedup = _num(warm.get("spawn_speedup"))
        if speedup is not None:
            series = "warm_start.spawn_speedup"
            key = (series, parsed.get("backend"))
            points.append(Point(series, key, round_label, speedup, "x"))
    superopt = parsed.get("superopt_ab")
    if isinstance(superopt, dict):
        batched = superopt.get("batched")
        batched = batched if isinstance(batched, dict) else {}
        stats = batched.get("proof_stats")
        queries = (stats.get("queries")
                   if isinstance(stats, dict) else None)
        speedup = _num(superopt.get("proof_speedup"))
        if speedup is not None:
            series = "superopt_ab.proof_speedup"
            key = (series, parsed.get("backend"), queries)
            points.append(Point(series, key, round_label, speedup, "x"))
        occupancy = _num(batched.get("mean_flush_occupancy"))
        if occupancy is not None:
            series = "superopt_ab.flush_occupancy"
            key = (series, parsed.get("backend"), queries)
            points.append(Point(series, key, round_label, occupancy,
                                "queries/flush"))
    slo = parsed.get("slo")
    if isinstance(slo, dict):
        for field in ("interactive_p99_ratio", "interactive_served_frac",
                      "cache_hit_rate"):
            field_value = _num(slo.get(field))
            if field_value is not None:
                series = f"slo.{field}"
                key = (series, parsed.get("backend"), slo.get("rate_hz"))
                points.append(Point(series, key, round_label,
                                    field_value, "frac"))
    corpus = parsed.get("corpus")
    if isinstance(corpus, dict):
        for engine in sorted(corpus):
            stats = corpus[engine]
            if not isinstance(stats, dict):
                continue
            for field, unit in (("median_states_per_sec", "states/s"),
                                ("total_swc_findings", "findings")):
                field_value = _num(stats.get(field))
                if field_value is not None:
                    series = f"corpus.{engine}.{field}"
                    key = (series, stats.get("budget_s"))
                    points.append(Point(series, key, round_label,
                                        field_value, unit))
    return points


def load_lineage(paths: List[str]) -> Tuple[List[Point], List[str]]:
    """Points from every readable round, plus notes for skipped ones."""
    points: List[Point] = []
    notes: List[str] = []
    for path in paths:
        label = os.path.splitext(os.path.basename(path))[0]
        label = label.replace("BENCH_", "")
        try:
            with open(path, encoding="utf-8") as handle:
                run = json.load(handle)
        except (OSError, ValueError) as error:
            notes.append(f"{label}: unreadable ({error})")
            continue
        extracted = extract_points(label, run)
        if not extracted:
            rc = run.get("rc")
            notes.append(f"{label}: no parsed payload (rc={rc}) — skipped")
        points.extend(extracted)
    return points, notes


def build_series(points: List[Point]) -> Dict[tuple, List[Point]]:
    """Points grouped by comparability key, lineage order preserved."""
    series: Dict[tuple, List[Point]] = {}
    for point in points:
        series.setdefault(point.key, []).append(point)
    return series


def find_regressions(series: Dict[tuple, List[Point]],
                     tolerance: float) -> List[Regression]:
    """Consecutive same-key drops beyond tolerance (all tracked numbers
    are higher-is-better)."""
    regressions: List[Regression] = []
    for key, run_points in series.items():
        for prev, cur in zip(run_points, run_points[1:]):
            if prev.value <= 0:
                continue  # nothing meaningful to compare against
            drop = (prev.value - cur.value) / prev.value
            if drop > tolerance:
                regressions.append(Regression(
                    cur.series, key, prev.round_label, prev.value,
                    cur.round_label, cur.value, drop))
    return regressions


def _fmt(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return f"{value:g}"


def render_trend(series: Dict[tuple, List[Point]], notes: List[str],
                 regressions: List[Regression],
                 tolerance: float) -> str:
    lines = [f"benchview — BENCH lineage trend (tolerance {tolerance:.0%})"]
    bad_keys = {(r.series, r.key, r.label) for r in regressions}
    for key in sorted(series, key=lambda k: (series[k][0].series, str(k))):
        run_points = series[key]
        first = run_points[0]
        config = ", ".join(str(part) for part in key[1:] if part is not None)
        header = first.series + (f" [{config}]" if config else "")
        rendered = []
        for prev, cur in zip([None] + run_points[:-1], run_points):
            cell = f"{cur.round_label}={_fmt(cur.value)}"
            if prev is not None and prev.value > 0:
                change = (cur.value - prev.value) / prev.value
                cell += f" ({change:+.0%})"
            if (cur.series, key, cur.round_label) in bad_keys:
                cell += " <-- REGRESSION"
            rendered.append(cell)
        unit = f" {first.unit}" if first.unit else ""
        lines.append(f"  {header}{unit}")
        lines.append("    " + "  ->  ".join(rendered))
    if notes:
        lines.append("  skipped rounds:")
        lines.extend(f"    {note}" for note in notes)
    if regressions:
        lines.append("  REGRESSIONS:")
        for reg in regressions:
            lines.append(
                f"    {reg.series}: {reg.prev_label}={_fmt(reg.prev_value)}"
                f" -> {reg.label}={_fmt(reg.value)}"
                f" ({-reg.drop:+.0%}, tolerance -{tolerance:.0%})")
    else:
        lines.append("  no regressions beyond tolerance")
    return "\n".join(lines)


def render_metrics(path: str) -> str:
    """Solver-latency quantiles + compile counts from a metrics
    snapshot (display-only; tolerant of missing keys)."""
    try:
        with open(path, encoding="utf-8") as handle:
            snapshot = json.load(handle)
    except (OSError, ValueError) as error:
        return f"  metrics snapshot {path}: unreadable ({error})"
    lines = [f"  metrics snapshot ({path}):"]
    flush = snapshot.get("dispatch.flush.latency_ms")
    if isinstance(flush, dict) and flush.get("count"):
        quantiles = "  ".join(
            f"{q}={_fmt(float(flush[q]))}ms"
            for q in ("p50", "p95", "p99") if q in flush)
        lines.append(f"    solver flush latency: {quantiles}"
                     f"  (n={flush['count']})")
    occupancy = snapshot.get("dispatch.flush.occupancy")
    if isinstance(occupancy, dict) and occupancy.get("count"):
        lines.append(f"    flush occupancy: avg={occupancy.get('avg', 0):.1f}"
                     f" p95={_fmt(float(occupancy.get('p95', 0)))}")
    compiles = snapshot.get("xla.bucket_compiles", 0)
    reuses = snapshot.get("xla.bucket_reuses", 0)
    lines.append(f"    compile counts: {int(compiles)} cold buckets,"
                 f" {int(reuses)} warm hits")
    if len(lines) == 1:
        lines.append("    (no tracked series in snapshot)")
    return "\n".join(lines)


def check_lineage(paths: List[str], tolerance: float,
                  metrics_path: Optional[str] = None) -> Tuple[str, int]:
    """(report text, exit code) for one lineage."""
    points, notes = load_lineage(paths)
    if not points and not notes:
        return "benchview: no BENCH_r*.json lineage found", 2
    series = build_series(points)
    regressions = find_regressions(series, tolerance)
    report = render_trend(series, notes, regressions, tolerance)
    if metrics_path and os.path.exists(metrics_path):
        report += "\n" + render_metrics(metrics_path)
    return report, (1 if regressions else 0)


def _selfcheck_round(directory: str, index: int, value: float) -> str:
    path = os.path.join(directory, f"BENCH_r{index:02d}.json")
    payload = {
        "n": index, "cmd": "selfcheck", "rc": 0, "tail": "",
        "parsed": {"metric": "sym_states_per_sec", "value": value,
                   "unit": "states/s", "backend": "cpu",
                   "n_branches": 10, "n_lanes": 128},
    }
    with open(path, "w", encoding="utf-8") as handle:
        json.dump(payload, handle)
    return path


def self_check(tolerance: float) -> int:
    """Fixture gate: a clean lineage must pass, an injected >=20%
    regression must fail. Proves the sentinel can actually fire."""
    with tempfile.TemporaryDirectory(prefix="benchview-") as tmp:
        clean = [_selfcheck_round(tmp, i + 1, v)
                 for i, v in enumerate((100.0, 105.0, 103.0))]
        report, code = check_lineage(clean, tolerance)
        if code != 0:
            print(report)
            print("benchview self-check: FAIL — clean lineage "
                  f"exited {code}, expected 0", file=sys.stderr)
            return 1
        regressed = [_selfcheck_round(tmp, 10 + i, v)
                     for i, v in enumerate((100.0, 102.0, 60.0))]
        report, code = check_lineage(regressed, tolerance)
        if code != 1:
            print(report)
            print("benchview self-check: FAIL — injected 41% regression "
                  f"exited {code}, expected 1", file=sys.stderr)
            return 1
    print("benchview self-check: ok (clean lineage passes, injected "
          "regression fails)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.benchview",
        description="Perf-regression sentinel over the BENCH_r*.json "
                    "lineage.")
    parser.add_argument("lineage", nargs="*",
                        help="BENCH round files, lineage order (default: "
                             "BENCH_r*.json at the repo root)")
    parser.add_argument("--tolerance", type=float, default=None,
                        help="relative drop that counts as a regression "
                             "(default: MYTHRIL_TPU_BENCH_TOLERANCE)")
    parser.add_argument("--metrics", default=None,
                        help="bench_metrics.json snapshot to render "
                             "solver-latency quantiles from (display "
                             "only)")
    parser.add_argument("--self-check", action="store_true",
                        help="verify the gate on fixture lineages "
                             "(clean -> 0, injected regression -> 1)")
    args = parser.parse_args(argv)

    tolerance = args.tolerance
    if tolerance is None:
        tolerance = tpu_config.get_float("MYTHRIL_TPU_BENCH_TOLERANCE")
    if tolerance <= 0:
        print("benchview: tolerance must be positive", file=sys.stderr)
        return 2

    if args.self_check:
        return self_check(tolerance)

    paths = args.lineage or sorted(
        glob.glob(os.path.join(_REPO, "BENCH_r*.json")))
    metrics_path = args.metrics
    if metrics_path is None:
        default_metrics = os.path.join(_REPO, "bench_metrics.json")
        if os.path.exists(default_metrics):
            metrics_path = default_metrics
    report, code = check_lineage(paths, tolerance, metrics_path)
    print(report)
    return code


if __name__ == "__main__":
    sys.exit(main())
