"""Taint-analysis smoke for the pre-merge gate (tools/check.sh).

Stdlib + in-repo frontends only (no jax import, no symbolic execution),
so it runs in a couple of seconds:

1. build the per-contract taint summary for both vendored headline
   contracts (killbilly, bectoken);
2. require non-empty sink tables, a converged fixpoint, and the
   dispatcher functions recovered;
3. run the module screen over the full CALLBACK module set and require
   at least one whole-module skip on at least one contract — the
   acceptance bar behind ``taint.screen.modules_skipped``.

Prints ``TAINT_SMOKE=ok`` on success; any failure exits non-zero with a
diagnostic.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from mythril_tpu.analysis import module_screen
    from mythril_tpu.analysis.module import ModuleLoader
    from mythril_tpu.analysis.module.base import EntryPoint
    from mythril_tpu.frontends.asm import assemble, dispatcher
    from mythril_tpu.frontends.disassembler import Disassembly
    from mythril_tpu.observe import metrics
    from mythril_tpu.staticanalysis import get_summary
    from tools.measure_headline import BECTOKEN, KILLBILLY

    modules = ModuleLoader().get_detection_modules(EntryPoint.CALLBACK)
    if not modules:
        print("taint_smoke: no CALLBACK modules loaded", file=sys.stderr)
        return 1

    any_skipped = False
    for name, spec in (("killbilly", KILLBILLY), ("bectoken", BECTOKEN)):
        disassembly = Disassembly(assemble(dispatcher(spec)).hex())
        summary = get_summary(disassembly)
        if summary is None:
            print(f"taint_smoke: no summary for {name}", file=sys.stderr)
            return 1
        if not summary.sink_sites:
            print(f"taint_smoke: empty sink table for {name}",
                  file=sys.stderr)
            return 1
        if not summary.converged:
            print(f"taint_smoke: fixpoint did not converge on {name}",
                  file=sys.stderr)
            return 1
        if len(summary.functions) < 2:
            print(f"taint_smoke: dispatcher not recovered for {name} "
                  f"({len(summary.functions)} function(s))",
                  file=sys.stderr)
            return 1
        kept, skipped = module_screen.screen_modules(modules, disassembly)
        if len(kept) + len(skipped) != len(modules):
            print(f"taint_smoke: screen lost modules on {name}",
                  file=sys.stderr)
            return 1
        print(f"taint_smoke: {name}: {len(summary.functions)} function(s), "
              f"{len(summary.sink_sites)} sink(s), "
              f"{len(skipped)} module(s) skipped"
              + (f" ({', '.join(sorted(type(m).__name__ for m in skipped))})"
                 if skipped else ""))
        any_skipped = any_skipped or bool(skipped)

    if not any_skipped:
        print("taint_smoke: no whole-module skip on any vendored "
              "contract", file=sys.stderr)
        return 1
    if metrics.snapshot().get("taint.screen.modules_skipped", 0) < 1:
        print("taint_smoke: taint.screen.modules_skipped not counted",
              file=sys.stderr)
        return 1
    print("TAINT_SMOKE=ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
