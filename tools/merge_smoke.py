"""State-merging smoke for the pre-merge gate (tools/check.sh).

One tiny reconverging-diamond contract through the device engine,
twice:

1. merge ON — require at least one ``frontier.merge.events`` (the
   post-dominator trigger, the merge kernel, and the ITE
   materialization all fired);
2. merge OFF (``support_args.state_merge = False``, the
   ``--no-state-merge`` path) — require zero merge events;
3. the two runs must report the same detections (selector-normalized
   witnesses: the merged path constraint is the weaker disjunction, so
   the solver may pick a different — still valid — model for the
   unconstrained branch word).

Prints ``MERGE_SMOKE=ok`` on success; any failure exits non-zero with a
diagnostic.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# tiny chunks put the merge boundary inside the lockstep window where
# both reconverged siblings sit on the join pc
os.environ["MYTHRIL_TPU_CHUNK"] = "2"
os.environ.setdefault("MYTHRIL_TPU_LANES", "16")

#: a reconverging diamond ahead of an unprotected SELFDESTRUCT — both
#: arms are the same length, so the fork siblings arrive at the join in
#: lockstep, and the SSTOREd arm value gives the pass a slot to blend
BRANCHY = {
    "boom()":
        "PUSH1 0x00\nCALLDATALOAD\nPUSH1 0x01\nAND\n"
        "PUSH @odd\nJUMPI\n"
        "PUSH1 0x07\nPUSH @join\nJUMP\n"
        "odd:\nJUMPDEST\nPUSH1 0x05\nJUMPDEST\n"
        "join:\nJUMPDEST\nPUSH1 0x00\nSSTORE\nJUMPDEST\n"
        "CALLER\nSELFDESTRUCT",
}


def _analyze(merge_flag: bool):
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)
    from mythril_tpu.observe import metrics
    from mythril_tpu.support.support_args import args as support_args

    support_args.state_merge = merge_flag
    metrics.reset("frontier.merge")
    reset_callback_modules()
    creation = creation_wrapper(assemble(dispatcher(BRANCHY)))
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=240, create_timeout=30, transaction_count=1,
        modules=["AccidentallyKillable"], compulsory_statespace=False,
        engine="tpu")
    issues = fire_lasers(wrapper, white_list=["AccidentallyKillable"])
    detections = sorted(
        (issue.swc_id, issue.address, issue.function,
         [step.get("input", "")[:10] for step in
          issue.transaction_sequence["steps"]])
        for issue in issues)
    return detections, metrics.snapshot()


def main() -> int:
    merged, snap_on = _analyze(True)
    unmerged, snap_off = _analyze(False)

    events = snap_on.get("frontier.merge.events", 0)
    retired = snap_on.get("frontier.merge.lanes_retired", 0)
    if events < 1 or retired < 1:
        print(f"merge_smoke: merged run reported no merge events "
              f"(events={events}, lanes_retired={retired})",
              file=sys.stderr)
        return 1
    if snap_off.get("frontier.merge.events", 0) != 0:
        print("merge_smoke: merge-off run still reported merge events",
              file=sys.stderr)
        return 1
    if merged != unmerged:
        print(f"merge_smoke: detection mismatch\n  on:  {merged}\n"
              f"  off: {unmerged}", file=sys.stderr)
        return 1
    if [d[0] for d in merged] != ["106"]:
        print(f"merge_smoke: expected one SWC-106 issue, got {merged}",
              file=sys.stderr)
        return 1
    print(f"merge_smoke: {events} merge event(s), {retired} lane(s) "
          f"retired, detections identical with merging off")
    print("MERGE_SMOKE=ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
