#!/usr/bin/env python
"""Back-compat shim over tpu-lint rules R1 (silent excepts) and R2
(dispatch bypass).

The two original ad-hoc rules now live in the rule-plugin framework under
``tools/lint/`` (see README "Static analysis"); this module keeps the
historical surface — ``check_file()``, ``check_device_calls()``,
``run()``, ``ALLOWLIST``, the ``(relpath, lineno, detail)`` violation
tuples, and exit status 1 from ``python tools/check_excepts.py`` — so
existing wiring (tests/test_lint_excepts.py, CI one-liners) keeps
working. New rules and new allowlist entries belong in ``tools/lint/``,
not here.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional, Tuple

if __package__ in (None, ""):  # run as a script / imported from tools/ dir
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.lint import LintContext
    from tools.lint.rules import dispatch_bypass as _r2
    from tools.lint.rules import silent_excepts as _r1
else:
    from .lint import LintContext
    from .lint.rules import dispatch_bypass as _r2
    from .lint.rules import silent_excepts as _r1

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

#: directories whose every .py file is linted (repo-relative)
SCAN_DIRS = _r1.SCAN_DIRS

#: audited (repo-relative path, enclosing function name) pairs — kept in
#: sync with the R1 entries in tools/lint/baseline.json
ALLOWLIST = {
    # finalizer: raising inside __del__ during interpreter shutdown turns a
    # leak into a spurious stderr traceback; close() is the loud path
    ("mythril_tpu/smt/solver/sat.py", "__del__"),
    # optional on-disk kernel cache: jax versions without a compilation
    # cache (or read-only home dirs) must not break import of the package
    ("mythril_tpu/parallel/__init__.py", "_enable_persistent_cache"),
}

#: device-solver entry points that must only be reached via the dispatch queue
DEVICE_ENTRYPOINTS = _r2.DEVICE_ENTRYPOINTS

#: the only files allowed to call DEVICE_ENTRYPOINTS directly (repo-relative)
DEVICE_CALLERS = _r2.DEVICE_CALLERS

#: rule-2 scan root: the whole package, not just SCAN_DIRS
DEVICE_SCAN_DIR = _r2.SCAN_DIR

_is_broad = _r1.is_broad
_is_silent = _r1.is_silent
_enclosing_function = _r1.enclosing_function


def _ctx() -> LintContext:
    return LintContext(REPO_ROOT)


def _parse(path: str):
    import ast

    with open(path, "r", encoding="utf-8") as handle:
        return ast.parse(handle.read(), filename=path)


def check_file(path: str) -> List[Tuple[str, int, str]]:
    """Rule 1 violations as (relpath, lineno, detail), ALLOWLIST applied."""
    relpath = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
    violations = _r1.check_file(relpath, _parse(path))
    return [v.as_tuple() for v in violations
            if (v.path, None if v.where == "<module>" else v.where)
            not in ALLOWLIST]


def check_device_calls(path: str) -> List[Tuple[str, int, str]]:
    """Rule 2: direct `solve_cnf_device[_batch](...)` calls outside the
    dispatch layer. Returns violations as (relpath, lineno, detail)."""
    relpath = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
    return [v.as_tuple()
            for v in _r2.check_file(relpath, _parse(path))]


def run() -> List[Tuple[str, int, str]]:
    ctx = _ctx()
    violations = []
    for path in ctx.iter_py(*SCAN_DIRS):
        violations.extend(check_file(path))
    for path in ctx.iter_py(DEVICE_SCAN_DIR):
        violations.extend(check_device_calls(path))
    return violations


def main() -> int:
    violations = run()
    for relpath, lineno, detail in violations:
        print(f"{relpath}:{lineno}: {detail}")
    if violations:
        print(f"\n{len(violations)} violation(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
