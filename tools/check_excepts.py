#!/usr/bin/env python
"""Lint: no new silent blanket exception swallows in the solver/device stack,
and no device-solver calls that bypass the batched dispatch layer.

Rule 1 — silent swallows: scans `mythril_tpu/smt/` and `mythril_tpu/parallel/`
for `except` handlers that are BOTH broad (bare `except:`,
`except Exception:`, or `except BaseException:`) AND silent (a body of only
`pass`/`continue`/`...`). A handler like that erases the entire failure story
the resilience subsystem exists to tell (support/resilience.py: every backend
failure must be classified, logged, and counted) — it is exactly the pattern
ISSUE 2 replaced at smt/solver/solver.py:48.

Audited survivors live in ALLOWLIST, keyed (file, enclosing def): sites
where swallowing is the correct behavior (e.g. a __del__ finalizer, where
raising during interpreter teardown is worse than any leak). Add a new
entry only with a comment defending it.

Rule 2 — dispatch bypass: scans all of `mythril_tpu/` for calls to
`solve_cnf_device` / `solve_cnf_device_batch` outside
smt/solver/dispatch.py (the batching queue that owns the resilience
contract: one breaker fire per batch, verdict caching, crosscheck sampling)
and parallel/jax_solver.py (the implementation itself). A direct call skips
the circuit breaker, the verdict cache, and the batch statistics — every
caller must go through `dispatch.submit()`/`dispatch.solve()`.

Run directly (`python tools/check_excepts.py`) or via the tier-1 suite
(tests/test_lint_excepts.py). Exit status 1 on violations.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Optional, Tuple

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

#: directories whose every .py file is linted (repo-relative)
SCAN_DIRS = ("mythril_tpu/smt", "mythril_tpu/parallel")

#: audited (repo-relative path, enclosing function name) pairs
ALLOWLIST = {
    # finalizer: raising inside __del__ during interpreter shutdown turns a
    # leak into a spurious stderr traceback; close() is the loud path
    ("mythril_tpu/smt/solver/sat.py", "__del__"),
    # optional on-disk kernel cache: jax versions without a compilation
    # cache (or read-only home dirs) must not break import of the package
    ("mythril_tpu/parallel/__init__.py", "_enable_persistent_cache"),
}

#: device-solver entry points that must only be reached via the dispatch queue
DEVICE_ENTRYPOINTS = ("solve_cnf_device", "solve_cnf_device_batch")

#: the only files allowed to call DEVICE_ENTRYPOINTS directly (repo-relative)
DEVICE_CALLERS = {
    "mythril_tpu/smt/solver/dispatch.py",
    "mythril_tpu/parallel/jax_solver.py",
}

#: rule-2 scan root: the whole package, not just SCAN_DIRS
DEVICE_SCAN_DIR = "mythril_tpu"

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    node = handler.type
    if node is None:
        return True
    if isinstance(node, ast.Name):
        return node.id in _BROAD
    if isinstance(node, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in node.elts)
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue)
               or (isinstance(stmt, ast.Expr)
                   and isinstance(stmt.value, ast.Constant)
                   and stmt.value.value is Ellipsis)
               for stmt in handler.body)


def _enclosing_function(tree: ast.AST, target: ast.ExceptHandler
                        ) -> Optional[str]:
    """Name of the innermost def/async def containing `target` (module
    level -> None)."""
    found: List[Optional[str]] = [None]

    def descend(node: ast.AST, current: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if child is target:
                found[0] = current
                return
            name = current
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            descend(child, name)

    descend(tree, None)
    return found[0]


def check_file(path: str) -> List[Tuple[str, int, str]]:
    """Returns violations as (relpath, lineno, detail)."""
    relpath = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as handle:
        tree = ast.parse(handle.read(), filename=path)
    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not (_is_broad(node) and _is_silent(node)):
            continue
        function = _enclosing_function(tree, node)
        if (relpath, function) in ALLOWLIST:
            continue
        where = function or "<module>"
        violations.append((
            relpath, node.lineno,
            f"silent blanket except in {where}() — classify and log the "
            "failure (support/resilience.py) or narrow the except; "
            "allowlist in tools/check_excepts.py only with justification"))
    return violations


def check_device_calls(path: str) -> List[Tuple[str, int, str]]:
    """Rule 2: direct `solve_cnf_device[_batch](...)` calls outside the
    dispatch layer. Returns violations as (relpath, lineno, detail)."""
    relpath = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
    if relpath in DEVICE_CALLERS:
        return []
    with open(path, "r", encoding="utf-8") as handle:
        tree = ast.parse(handle.read(), filename=path)
    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name not in DEVICE_ENTRYPOINTS:
            continue
        violations.append((
            relpath, node.lineno,
            f"direct {name}() call bypasses the batched dispatch layer "
            "(breaker, verdict cache, crosscheck sampling) — go through "
            "smt/solver/dispatch.submit()/solve() instead"))
    return violations


def run() -> List[Tuple[str, int, str]]:
    violations = []
    for scan_dir in SCAN_DIRS:
        base = os.path.join(REPO_ROOT, scan_dir)
        for dirpath, _, filenames in os.walk(base):
            for filename in sorted(filenames):
                if filename.endswith(".py"):
                    violations.extend(
                        check_file(os.path.join(dirpath, filename)))
    base = os.path.join(REPO_ROOT, DEVICE_SCAN_DIR)
    for dirpath, _, filenames in os.walk(base):
        for filename in sorted(filenames):
            if filename.endswith(".py"):
                violations.extend(
                    check_device_calls(os.path.join(dirpath, filename)))
    return violations


def main() -> int:
    violations = run()
    for relpath, lineno, detail in violations:
        print(f"{relpath}:{lineno}: {detail}")
    if violations:
        print(f"\n{len(violations)} silent blanket except(s) found")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
