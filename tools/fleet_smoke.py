"""Fleet-mode smoke for the pre-merge gate (tools/check.sh).

Packs two tiny single-transaction contracts — a reconverging
selfdestruct diamond (SWC-106) and an additive-overflow store
(SWC-101), merge_smoke-sized so the whole A/B fits the gate budget —
into ONE device fleet (MythrilAnalyzer fleet_contract_results ->
parallel/frontier.py FleetDriver) and checks the tentpole's two
promises:

1. **Parity**: per-contract detections from the fleet run are identical
   to two sequential runs of the same corpus (same process, same knobs —
   the per-turn singleton swap must make each member's namespace
   indistinguishable from a solo run's);
2. **Shared dispatch**: at least one batched solver flush carried
   queries from BOTH contracts (dispatch.shared_flush_count), proving
   the merged solver traffic actually shares device launches.

Prints ``FLEET_SMOKE=ok`` on success; any failure exits non-zero with a
diagnostic.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("MYTHRIL_TPU_LANES", "16")
# escape-time feasibility pruning is the device-phase solver traffic that
# both contracts contribute to one queue; a high flush threshold lets the
# batch fill from both members before the first demanded result ships it
os.environ.setdefault("MYTHRIL_TPU_CHECK_ESCAPES", "1")
os.environ.setdefault("MYTHRIL_TPU_BATCH_FLUSH", "64")
# the 50 ms age flush would split the cross-member prefetch union into
# timing-dependent fragments on slow CPU host turns — park it so the
# shared-flush assertion sees the merged batch, not its shrapnel
os.environ.setdefault("MYTHRIL_TPU_BATCH_AGE_MS", "60000")
# the gate runs on CPU, where a host-emulated device SAT solve over real
# path cones takes minutes per flush: cap the device lane out so every
# query falls back (loudly, counted) to native CDCL. Flush composition —
# the thing this smoke asserts — is accounted before the solve either
# way; actual device solving is TPU-only per the BASELINE round-8 policy.
os.environ.setdefault("MYTHRIL_TPU_DEVICE_CLAUSE_CAP", "1")

MODULES = ["AccidentallyKillable", "IntegerArithmetics"]
TX_COUNT = 1

#: reconverging diamond ahead of an unprotected SELFDESTRUCT (the
#: merge_smoke shape) — SWC-106 in one transaction
BRANCHY = {
    "boom()":
        "PUSH1 0x00\nCALLDATALOAD\nPUSH1 0x01\nAND\n"
        "PUSH @odd\nJUMPI\n"
        "PUSH1 0x07\nPUSH @join\nJUMP\n"
        "odd:\nJUMPDEST\nPUSH1 0x05\nJUMPDEST\n"
        "join:\nJUMPDEST\nPUSH1 0x00\nSSTORE\nJUMPDEST\n"
        "CALLER\nSELFDESTRUCT",
}

#: two symbolic calldata words ADDed and stored — SWC-101 in one
#: transaction
ADDFLOW = {
    "bump()":
        "PUSH1 0x04\nCALLDATALOAD\nPUSH1 0x24\nCALLDATALOAD\nADD\n"
        "PUSH1 0x00\nSSTORE\n"
        "PUSH1 0x01\nPUSH1 0x00\nMSTORE\nPUSH1 0x20\nPUSH1 0x00\nRETURN",
}


def _corpus():
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)

    return [
        ("branchy", creation_wrapper(assemble(dispatcher(BRANCHY))).hex()),
        ("addflow", creation_wrapper(assemble(dispatcher(ADDFLOW))).hex()),
    ]


def _analyze(fleet: bool):
    """One corpus run; returns {contract: sorted detection digests}."""
    from mythril_tpu.analysis.security import reset_callback_modules
    from mythril_tpu.mythril import MythrilAnalyzer, MythrilDisassembler
    from mythril_tpu.smt.solver.solver import reset_solver_backend

    reset_solver_backend()
    reset_callback_modules()
    disassembler = MythrilDisassembler()
    address = None
    for name, code in _corpus():
        address, contract = disassembler.load_from_bytecode(code, False)
        contract.name = name

    class Cmd:
        pass

    cmd = Cmd()
    cmd.engine = "tpu"
    cmd.solver = "jax"
    cmd.fleet = fleet
    cmd.execution_timeout = 240
    cmd.create_timeout = 60
    cmd.max_depth = 128
    analyzer = MythrilAnalyzer(disassembler, cmd_args=cmd, strategy="bfs",
                               address=address)
    report = analyzer.fire_lasers(modules=MODULES,
                                  transaction_count=TX_COUNT)
    digests = {}
    for _, issue in sorted(report.issues.items()):
        digests.setdefault(issue.contract, []).append(
            (issue.swc_id, issue.address, issue.function,
             [step.get("input", "")[:10] for step in
              issue.transaction_sequence["steps"]]))
    for detections in digests.values():
        detections.sort()
    return digests


def main() -> int:
    from mythril_tpu.smt.solver import dispatch

    sequential = _analyze(fleet=False)
    shared_before = dispatch.shared_flush_count()
    fleet = _analyze(fleet=True)
    shared = dispatch.shared_flush_count() - shared_before

    if not any(sequential.values()):
        print(f"fleet_smoke: sequential baseline found no issues: "
              f"{sequential}", file=sys.stderr)
        return 1
    if fleet != sequential:
        print(f"fleet_smoke: detection mismatch\n  sequential: "
              f"{sequential}\n  fleet:      {fleet}", file=sys.stderr)
        return 1
    if shared < 1:
        print("fleet_smoke: no shared dispatch flush — the fleet run "
              "never mixed both contracts' queries into one device batch",
              file=sys.stderr)
        return 1
    issues = sum(len(v) for v in fleet.values())
    print(f"fleet_smoke: {issues} detection(s) across {len(fleet)} "
          f"contract(s) identical to sequential; {shared} shared "
          f"dispatch flush(es)")
    print("FLEET_SMOKE=ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
