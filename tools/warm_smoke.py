"""Durable-warmth smoke for the pre-merge gate (tools/check.sh).

Two-process cold→warm replay against a private warmset directory
(CPU-only, tiny CNF corpus, so it stays cheap):

1. **cold** (child 1): pushes a small CNF corpus through the batched
   device dispatch — every shape bucket pays its ``xla.bucket_compiles``
   compile, and ``parallel/exec_cache.py`` persists each compiled
   runner beside the manifest — then ``WarmSet.record_observed()``
   writes the shape manifest and the verdict sidecar.
2. **warm** (child 2, a fresh interpreter): ``WarmSet.warmup()`` must
   be deserialize-only — **zero** ``xla.bucket_compiles``, executable
   cache hits > 0, verdicts loaded > 0, and respawn-to-ready under the
   2 s acceptance bound — and a replay of the same corpus must answer
   from the imported verdict cache (``dispatch.cache_hits`` > 0) with
   the compile counter still at zero.

The two children share only the on-disk stores (warmset manifest,
``exec_cache/`` payloads, verdict sidecar, persistent XLA cache), so a
pass proves a respawned worker really is a cache read, not a recompile.

Prints ``WARM_SMOKE=ok`` on success; any failure exits non-zero with a
diagnostic. The caller bounds the wall clock (check.sh wraps this in
`timeout`)."""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Acceptance bound: a warm respawn must reach ready inside this wall.
WARM_READY_BUDGET_S = 2.0

#: Tiny deterministic corpus — small enough that the cold compile stays
#: in CI budget, varied enough to exercise SAT and UNSAT verdicts.
_CORPUS = [
    # SAT: (x1 | x2) & (!x1 | x2) & (x1 | !x2)  -> x1=x2=True
    ([[1, 2], [-1, 2], [1, -2]], 2),
    # UNSAT: x1 & !x1
    ([[1], [-1]], 1),
    # SAT: 3 vars, mixed widths
    ([[1, 2, 3], [-1, -2], [2, 3]], 3),
]


def _solve_corpus() -> list:
    """Run the corpus through the batched device dispatch; returns the
    verdict list (dispatch caches every SAT/UNSAT on the way)."""
    from mythril_tpu.smt.solver import dispatch

    futures = [dispatch.submit(clauses, n_vars, max_conflicts=4096)
               for clauses, n_vars in _CORPUS]
    dispatch.flush()
    return [future.result()[0] for future in futures]


def _run_cold(manifest: str) -> int:
    from mythril_tpu.observe import metrics
    from mythril_tpu.serve.warmset import WarmSet
    from mythril_tpu.smt.solver import dispatch, sat

    verdicts = _solve_corpus()
    decided = [v for v in verdicts if v in (sat.SAT, sat.UNSAT)]
    if not decided:
        print(f"cold: no decided verdicts (got {verdicts}) — nothing to "
              "persist", file=sys.stderr)
        return 1
    if not dispatch.export_verdicts():
        print("cold: verdict cache is empty after decided solves",
              file=sys.stderr)
        return 1
    compiles = int(metrics.value("xla.bucket_compiles"))
    if compiles < 1:
        print("cold: expected at least one bucket compile, saw "
              f"{compiles}", file=sys.stderr)
        return 1
    WarmSet(manifest).record_observed()
    cache_dir = os.environ["MYTHRIL_TPU_EXEC_CACHE_DIR"]
    stored = [f for f in os.listdir(cache_dir) if f.endswith(".jexec")] \
        if os.path.isdir(cache_dir) else []
    if not stored:
        print(f"cold: no serialized executables in {cache_dir}",
              file=sys.stderr)
        return 1
    print(json.dumps({"compiles": compiles, "stored": len(stored),
                      "verdicts": len(dispatch.export_verdicts())}))
    return 0


def _run_warm(manifest: str) -> int:
    from mythril_tpu.observe import metrics
    from mythril_tpu.serve.warmset import WarmSet
    from mythril_tpu.smt.solver import dispatch, sat

    warmset = WarmSet(manifest)
    started = time.perf_counter()
    warmed = warmset.warmup()
    ready_wall = time.perf_counter() - started

    problems = []
    compiles = int(metrics.value("xla.bucket_compiles"))
    exec_hits = int(metrics.value("cache.exec.hits"))
    if warmed < 1:
        problems.append(f"warmed {warmed} buckets, expected >= 1")
    if compiles != 0:
        problems.append(f"warm respawn paid {compiles} bucket compile(s), "
                        "expected 0 (deserialize-only)")
    if exec_hits < 1:
        problems.append(f"executable cache hits {exec_hits}, expected >= 1")
    if warmset.loaded_verdicts < 1:
        problems.append(f"loaded {warmset.loaded_verdicts} verdicts, "
                        "expected >= 1")
    if ready_wall >= WARM_READY_BUDGET_S:
        problems.append(f"respawn-to-ready took {ready_wall:.2f}s, budget "
                        f"{WARM_READY_BUDGET_S:.1f}s")

    # replay: every corpus verdict must come from the imported cache,
    # and the replay itself must not trigger a compile
    verdicts = _solve_corpus()
    decided = [v for v in verdicts if v in (sat.SAT, sat.UNSAT)]
    verdict_hits = int(metrics.value("dispatch.cache_hits"))
    if len(decided) != len(_CORPUS):
        problems.append(f"replay decided {len(decided)}/{len(_CORPUS)} "
                        "corpus queries")
    if verdict_hits < 1:
        problems.append(f"replay verdict-cache hits {verdict_hits}, "
                        "expected >= 1")
    replay_compiles = int(metrics.value("xla.bucket_compiles"))
    if replay_compiles != 0:
        problems.append(f"replay paid {replay_compiles} bucket compile(s), "
                        "expected 0")

    for problem in problems:
        print(f"warm: {problem}", file=sys.stderr)
    if problems:
        return 1
    print(json.dumps({"ready_s": round(ready_wall, 3), "warmed": warmed,
                      "exec_hits": exec_hits,
                      "verdicts_loaded": warmset.loaded_verdicts,
                      "verdict_hits": verdict_hits}))
    return 0


def _run_ready(manifest: str) -> int:
    """Neutral spawn-to-ready timing (no asserts): bench.py's
    ``warm_start`` phase runs this twice — once against an empty
    executable cache (cold respawn) and once against the seeded one —
    and reports the ratio as the spawn speedup."""
    from mythril_tpu.observe import metrics
    from mythril_tpu.serve.warmset import WarmSet

    warmset = WarmSet(manifest)
    started = time.perf_counter()
    warmed = warmset.warmup()
    print(json.dumps({
        "ready_s": round(time.perf_counter() - started, 3),
        "warmed": warmed,
        "compiles": int(metrics.value("xla.bucket_compiles")),
        "exec_hits": int(metrics.value("cache.exec.hits")),
        "verdicts_loaded": warmset.loaded_verdicts}))
    return 0


def _child(phase: str, workdir: str) -> subprocess.CompletedProcess:
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        MYTHRIL_TPU_SERVE_MANIFEST=os.path.join(workdir, "warmset.json"),
        MYTHRIL_TPU_EXEC_CACHE_DIR=os.path.join(workdir, "exec_cache"),
        MYTHRIL_TPU_JAX_CACHE=os.path.join(workdir, "xla_cache"))
    return subprocess.run(
        [sys.executable, "-m", "tools.warm_smoke", "--phase", phase,
         "--manifest", env["MYTHRIL_TPU_SERVE_MANIFEST"]],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=300)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.warm_smoke",
        description="two-process durable-warmth gate (cold compile+persist, "
                    "then a fresh process must respawn warm)")
    parser.add_argument("--phase", choices=("cold", "warm", "ready"),
                        default=None,
                        help="internal: run one child phase in-process")
    parser.add_argument("--manifest", default=None)
    args = parser.parse_args(argv)

    if args.phase == "cold":
        return _run_cold(args.manifest)
    if args.phase == "warm":
        return _run_warm(args.manifest)
    if args.phase == "ready":
        return _run_ready(args.manifest)

    workdir = tempfile.mkdtemp(prefix="warm_smoke_")
    for phase in ("cold", "warm"):
        started = time.perf_counter()
        result = _child(phase, workdir)
        wall = time.perf_counter() - started
        if result.returncode != 0:
            sys.stderr.write(result.stdout)
            sys.stderr.write(result.stderr)
            print(f"WARM_SMOKE={phase} phase failed "
                  f"(rc={result.returncode})", file=sys.stderr)
            return 1
        print(f"{phase}: {result.stdout.strip().splitlines()[-1]} "
              f"({wall:.1f}s)")
    print("WARM_SMOKE=ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
