"""load_smoke — the overload-resilience SLO gate (tools/check.sh).

Runs tools/loadgen.py's SLO A/B — a short uncontended interactive-only
baseline, then a ~30 s contended run (1 worker elastic to 2, small
admission queue, bulk demand past capacity, duplicate-heavy mix) — and
asserts the PR's acceptance invariants:

1. **zero interactive requests shed** — every ``overloaded`` reply
   landed on bulk traffic; the priority queue protected the class that
   matters;
2. **bulk absorbed the shedding** — the flood actually overloaded the
   daemon (>=1 bulk shed), so invariant 1 was tested under pressure,
   not in an idle daemon;
3. **interactive p99 bounded** — the contended interactive p99 stays
   within max(2x, +5 s) of the uncontended baseline p99 (the +5 s floor
   absorbs shared-CI scheduling noise on sub-second baselines; the 2x
   bound is the real SLO once baselines grow);
4. **>=1 autoscale-up** — the backlog drove the supervisor pool past
   its starting size through the hysteresis controller;
5. **>=1 result-store hit** — a repeat codehash was answered from the
   content-addressed store without a worker dispatch.

Exit 0 with ``{"ok": true, ...}`` on stdout, exit 1 with the failed
invariants listed. Wall-clock budget ~2-3 min including daemon spawns.
"""

from __future__ import annotations

import json
import sys

from tools import loadgen


def main() -> int:
    ab = loadgen.slo_ab()
    slo = ab["slo"]
    contended = ab["contended"]
    classes = contended["classes"]
    autoscale = contended["autoscale"]
    cache = contended["cache"]

    problems = []
    if classes["interactive"]["shed"] != 0:
        problems.append(
            f"{classes['interactive']['shed']} interactive request(s) "
            f"shed — the priority queue must only ever shed bulk")
    if classes["bulk"]["shed"] < 1:
        problems.append(
            "no bulk request was shed: the flood never overloaded the "
            "daemon, so the interactive-protection invariant went "
            "untested (raise --rate or shrink --queue-max)")
    base_p99 = slo["baseline_interactive_p99_ms"]
    load_p99 = slo["contended_interactive_p99_ms"]
    p99_bound = max(2.0 * base_p99, base_p99 + 5000.0)
    if load_p99 > p99_bound:
        problems.append(
            f"contended interactive p99 {load_p99:.0f}ms exceeds "
            f"{p99_bound:.0f}ms (uncontended baseline {base_p99:.0f}ms)")
    transport = [outcome
                 for name in classes
                 for outcome, count in classes[name]["outcomes"].items()
                 if outcome.startswith("transport:") for _ in range(count)]
    if transport:
        problems.append(f"{len(transport)} transport failure(s): "
                        f"{transport[:5]} — replies must be typed sheds, "
                        f"never dropped connections")
    if not autoscale["scale_ups"]:
        problems.append("autoscaler never scaled up under a sustained "
                        "backlog (expected pool 1 -> 2)")
    if (autoscale["peak_pool"] or 0) < 2:
        problems.append(f"pool never actually grew (peak "
                        f"{autoscale['peak_pool']}, expected >= 2)")
    if not cache["store_hits"]:
        problems.append("result store answered zero repeat codehashes "
                        "in a duplicate-heavy mix")

    verdict = {
        "ok": not problems,
        "problems": problems,
        "slo": slo,
        "interactive": {k: classes["interactive"][k]
                        for k in ("sent", "ok", "shed", "p50_ms",
                                  "p95_ms", "p99_ms")},
        "bulk": {k: classes["bulk"][k]
                 for k in ("sent", "ok", "shed", "shed_rate")},
        "autoscale": autoscale,
        "cache": cache,
    }
    print(json.dumps(verdict, sort_keys=True), flush=True)
    if problems:
        for problem in problems:
            print(f"load_smoke: FAIL — {problem}", file=sys.stderr)
        return 1
    print(f"load_smoke: ok — {classes['interactive']['sent']} interactive "
          f"all served (0 shed, p99 {load_p99:.0f}ms vs baseline "
          f"{base_p99:.0f}ms), bulk shed {classes['bulk']['shed']}/"
          f"{classes['bulk']['sent']}, "
          f"{autoscale['scale_ups']} scale-up(s) to pool "
          f"{autoscale['peak_pool']}, "
          f"{cache['store_hits']} result-store hit(s)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
