#!/usr/bin/env python
"""Phase-level profile of the TPU frontier on the bench stress workload:
how much of the wall clock goes to fused device steps vs host services vs
transfers vs the host continuation. Run on the real chip:

    python tools/profile_frontier.py [seconds] [lanes]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("MYTHRIL_TPU_LANES", "512")

import numpy as np

TIMES = {"step": 0.0, "service": 0.0, "to_device": 0.0,
         "materialize": 0.0, "exec_host": 0.0}
COUNTS = {"chunks": 0, "services": 0, "materialized_calls": 0}


def patch():
    import jax

    from mythril_tpu.parallel import frontier, symstep

    real_step = symstep.run_chunk
    real_to_device = frontier._Frontier._to_device
    real_mat = frontier._Frontier._materialize_lanes
    real_fetch = frontier._Frontier._fetch_escapes
    real_flush = frontier._Frontier._flush_backlog

    def timed_step(state, planes, arena, sched, chunk):
        t0 = time.perf_counter()
        out = real_step(state, planes, arena, sched, chunk)
        jax.block_until_ready(out[0].status)
        TIMES["step"] += time.perf_counter() - t0
        COUNTS["chunks"] += 1
        return out

    def timed_to_device(self, state, planes):
        t0 = time.perf_counter()
        out = real_to_device(self, state, planes)
        TIMES["to_device"] += time.perf_counter() - t0
        return out

    def timed_mat(self, state, planes, harena, lanes):
        t0 = time.perf_counter()
        out = real_mat(self, state, planes, harena, lanes)
        TIMES["materialize"] += time.perf_counter() - t0
        COUNTS["materialized_calls"] += len(lanes)
        return out

    def timed_fetch(self, sched, esc_count, *a, **k):
        t0 = time.perf_counter()
        out = real_fetch(self, sched, esc_count, *a, **k)
        TIMES["service"] += time.perf_counter() - t0
        COUNTS["services"] += 1
        return out

    def timed_flush(self, backlog):
        t0 = time.perf_counter()
        out = real_flush(self, backlog)
        TIMES["materialize"] += time.perf_counter() - t0
        if backlog is not None:
            COUNTS["materialized_calls"] += backlog[2]
        return out

    frontier._Frontier._fetch_escapes = timed_fetch
    frontier._Frontier._flush_backlog = timed_flush
    symstep.run_chunk = timed_step
    frontier.symstep.run_chunk = timed_step
    frontier._Frontier._to_device = timed_to_device
    frontier._Frontier._materialize_lanes = timed_mat


def main():
    seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 30.0
    if len(sys.argv) > 2:
        os.environ["MYTHRIL_TPU_LANES"] = sys.argv[2]

    import logging

    logging.basicConfig(level=logging.INFO)

    import bench

    # warm the compile outside the measured window
    os.environ["MYTHRIL_TPU_MAX_STEPS"] = "16"
    os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"] = "1"
    bench._run_engine("tpu", 120)
    del os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"]
    os.environ["MYTHRIL_TPU_MAX_STEPS"] = "4096"

    patch()
    from mythril_tpu.core import svm

    real_exec = svm.LaserEVM.exec

    def timed_exec(self, *a, **k):
        t0 = time.perf_counter()
        out = real_exec(self, *a, **k)
        TIMES["exec_host"] += time.perf_counter() - t0
        return out

    svm.LaserEVM.exec = timed_exec

    t0 = time.perf_counter()
    rate, info = bench._run_engine("tpu", seconds)
    wall = time.perf_counter() - t0
    print({"rate": round(rate, 1), **info})
    print({"wall_s": round(wall, 2),
           **{k: round(v, 2) for k, v in TIMES.items()}, **COUNTS})
    print({"unaccounted_s": round(wall - sum(TIMES.values()), 2)})


if __name__ == "__main__":
    main()
