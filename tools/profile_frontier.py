#!/usr/bin/env python
"""Phase-level profile of the TPU frontier on the bench stress workload:
how much of the wall clock goes to fused device steps vs host services vs
transfers vs the host continuation. Run on the real chip:

    python tools/profile_frontier.py [seconds] [lanes]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("MYTHRIL_TPU_LANES", "512")

import numpy as np

TIMES = {"step": 0.0, "service": 0.0, "to_device": 0.0,
         "materialize": 0.0, "exec_host": 0.0}
COUNTS = {"chunks": 0, "services": 0, "materialized_calls": 0}


def patch():
    import jax

    from mythril_tpu.parallel import frontier, symstep

    real_step = symstep.sym_step_many_counted
    real_service = frontier._Frontier._service
    real_to_device = frontier._Frontier._to_device
    real_mat = frontier._Frontier._materialize_lanes

    def timed_step(state, planes, arena, chunk):
        t0 = time.perf_counter()
        out = real_step(state, planes, arena, chunk)
        jax.block_until_ready(out[0].status)
        TIMES["step"] += time.perf_counter() - t0
        COUNTS["chunks"] += 1
        return out

    def timed_service(self, state, planes):
        t0 = time.perf_counter()
        out = real_service(self, state, planes)
        TIMES["service"] += time.perf_counter() - t0
        COUNTS["services"] += 1
        return out

    def timed_to_device(self, state, planes):
        t0 = time.perf_counter()
        out = real_to_device(self, state, planes)
        TIMES["to_device"] += time.perf_counter() - t0
        return out

    def timed_mat(self, state, planes, harena, lanes):
        t0 = time.perf_counter()
        out = real_mat(self, state, planes, harena, lanes)
        TIMES["materialize"] += time.perf_counter() - t0
        COUNTS["materialized_calls"] += len(lanes)
        return out

    symstep.sym_step_many_counted = timed_step
    frontier.symstep.sym_step_many_counted = timed_step
    frontier._Frontier._service = timed_service
    frontier._Frontier._to_device = timed_to_device
    frontier._Frontier._materialize_lanes = timed_mat


def main():
    seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 30.0
    if len(sys.argv) > 2:
        os.environ["MYTHRIL_TPU_LANES"] = sys.argv[2]

    import logging

    logging.basicConfig(level=logging.INFO)

    import bench

    # warm the compile outside the measured window
    os.environ["MYTHRIL_TPU_MAX_STEPS"] = "16"
    os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"] = "1"
    bench._run_engine("tpu", 120)
    del os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"]
    os.environ["MYTHRIL_TPU_MAX_STEPS"] = "4096"

    patch()
    from mythril_tpu.core import svm

    real_exec = svm.LaserEVM.exec

    def timed_exec(self, *a, **k):
        t0 = time.perf_counter()
        out = real_exec(self, *a, **k)
        TIMES["exec_host"] += time.perf_counter() - t0
        return out

    svm.LaserEVM.exec = timed_exec

    t0 = time.perf_counter()
    rate, info = bench._run_engine("tpu", seconds)
    wall = time.perf_counter() - t0
    print({"rate": round(rate, 1), **info})
    print({"wall_s": round(wall, 2),
           **{k: round(v, 2) for k, v in TIMES.items()}, **COUNTS})
    accounted = sum(TIMES.values()) - TIMES["materialize"]  # nested in service
    print({"unaccounted_s": round(wall - accounted, 2)})


if __name__ == "__main__":
    main()
