#!/usr/bin/env python
"""Phase-level profile of the TPU frontier on the bench stress workload:
how much of the wall clock goes to fused device steps vs host services vs
transfers vs the host continuation, plus the device telemetry rollup
(executed ops, forks, escapes, mean lane occupancy). Run on the real chip:

    python tools/profile_frontier.py [seconds] [lanes]

Built on the observe/ spans the frontier already emits (frontier.chunk,
frontier.sync, frontier.fetch_escapes, frontier.host_drain, ...) and the
device-resident telemetry plane — no monkeypatched timing shims, so the
profiled run is byte-identical to a production `--trace-out` run.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("MYTHRIL_TPU_LANES", "512")

#: wall-clock phases, as (report key, span names rolled into it)
PHASES = (
    ("step", ("frontier.chunk",)),
    ("sync", ("frontier.sync",)),
    ("service", ("frontier.fetch_escapes", "frontier.service_cold")),
    ("seed", ("frontier.seed",)),
    ("materialize", ("frontier.host_drain",)),
    ("exec_host", ("frontier.host_continuation",)),
)

#: frontier.telemetry.* counters included in the report
TELEMETRY = ("executed", "forks", "escapes", "reseeds", "deaths",
             "cold_sload_pauses")


def _span_rollup(trace_path):
    """name -> (count, total_seconds) over the trace's X events."""
    with open(trace_path, "r", encoding="utf-8") as handle:
        events = json.load(handle)["traceEvents"]
    rollup = {}
    for event in events:
        if event.get("ph") != "X":
            continue
        count, total = rollup.get(event["name"], (0, 0.0))
        rollup[event["name"]] = (count + 1,
                                 total + float(event.get("dur", 0.0)) / 1e6)
    return rollup


def main():
    seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 30.0
    if len(sys.argv) > 2:
        os.environ["MYTHRIL_TPU_LANES"] = sys.argv[2]

    import logging

    logging.basicConfig(level=logging.INFO)

    import bench
    from mythril_tpu.observe import metrics, trace

    # warm the compile outside the measured window (work-bounded: a few
    # fused chunks, no host continuation)
    os.environ["MYTHRIL_TPU_MAX_STEPS"] = "16"
    os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"] = "1"
    bench._run_engine("tpu", 120)
    del os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"]
    os.environ["MYTHRIL_TPU_MAX_STEPS"] = "4096"

    trace_path = os.path.join(tempfile.mkdtemp(prefix="profile_frontier_"),
                              "trace.json")
    trace.enable(trace_path)
    tel_before = {name: metrics.value(f"frontier.telemetry.{name}")
                  for name in TELEMETRY}

    t0 = time.perf_counter()
    rate, info = bench._run_engine("tpu", seconds)
    wall = time.perf_counter() - t0
    trace.export()
    trace.disable()

    rollup = _span_rollup(trace_path)
    times = {}
    counts = {}
    for key, span_names in PHASES:
        times[key] = sum(rollup.get(name, (0, 0.0))[1]
                         for name in span_names)
        counts[key] = sum(rollup.get(name, (0, 0.0))[0]
                          for name in span_names)
    telemetry = {
        name: int(metrics.value(f"frontier.telemetry.{name}")
                  - tel_before[name])
        for name in TELEMETRY}
    occupancy = metrics.value("frontier.telemetry.occupancy")

    print({"rate": round(rate, 1), **info})
    print({"wall_s": round(wall, 2),
           **{k: round(v, 2) for k, v in times.items()},
           "chunks": counts["step"], "services": counts["service"],
           "drains": counts["materialize"]})
    # step+sync overlap inside frontier.chunk windows is possible only for
    # nested spans; these six are disjoint phases of the run loop
    print({"unaccounted_s": round(wall - sum(times.values()), 2)})
    print({"telemetry": telemetry,
           "mean_lane_occupancy": round(float(occupancy), 1),
           "trace": trace_path})


if __name__ == "__main__":
    main()
