"""CLI for tpu-lint: ``python -m tools.lint``.

Exit status 1 on any active violation, stale baseline entry, or
unjustified baseline entry; 0 on a clean tree.

    python -m tools.lint                      # run every rule
    python -m tools.lint --list-rules         # rule inventory
    python -m tools.lint --rule R3 --rule R5  # subset
    python -m tools.lint --json               # machine-readable findings
    python -m tools.lint PATH [PATH ...]      # file-scoped run: each
        rule's per-file checker over just those files (fixtures,
        pre-commit); baseline hygiene is skipped on partial views
    python -m tools.lint --baseline-update    # refresh baseline.json:
        keeps justifications for keys that still fire, drops stale keys,
        adds UNJUSTIFIED placeholders (which still fail the lint) for new
        ones — intentional allowlist growth is always an explicit diff.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (DEFAULT_BASELINE, Baseline, RuleDiscovery, run_lint,
               run_rules)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="tpu-lint: repo-specific static-analysis rules")
    parser.add_argument("--list-rules", action="store_true",
                        help="list installed rules and exit")
    parser.add_argument("--rule", action="append", metavar="CODE",
                        help="run only this rule (repeatable), e.g. R3")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as JSON")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        metavar="PATH", help="baseline file "
                        "(default tools/lint/baseline.json)")
    parser.add_argument("--baseline-update", action="store_true",
                        help="rewrite the baseline from current findings")
    parser.add_argument("paths", nargs="*", metavar="PATH",
                        help="lint only these files (file-scoped rule "
                        "checkers; default: the whole tree)")
    args = parser.parse_args(argv)

    discovery = RuleDiscovery()
    if args.list_rules:
        for code, cls in discovery.installed_rules.items():
            print(f"{code}  {cls.name:<18} {cls.description}")
        return 0

    if args.baseline_update:
        rules = discovery.get_rules(args.rule)
        raw = run_rules(rules)
        baseline = Baseline.load(args.baseline)
        before = set(baseline.entries)
        baseline.update_from(raw)
        baseline.save(args.baseline)
        added = sorted(set(baseline.entries) - before)
        dropped = sorted(before - set(baseline.entries))
        print(f"baseline updated: {len(baseline.entries)} entries "
              f"({len(added)} added, {len(dropped)} dropped)")
        for key in added:
            print(f"  + {key}  (UNJUSTIFIED — write a justification)")
        for key in dropped:
            print(f"  - {key}")
        return 0

    paths = [os.path.abspath(p) for p in args.paths] or None
    report = run_lint(args.rule, baseline_path=args.baseline, paths=paths)
    if args.as_json:
        print(json.dumps({
            "violations": [v.as_dict() for v in report.violations],
            "suppressed": [v.as_dict() for v in report.suppressed],
            "stale_baseline_keys": report.stale_keys,
            "unjustified_baseline_keys": report.unjustified_keys,
            "ok": report.ok,
        }, indent=2))
        return 0 if report.ok else 1

    for violation in report.violations:
        print(f"{violation.path}:{violation.lineno}: [{violation.rule}] "
              f"{violation.detail}")
    for key in report.stale_keys:
        print(f"baseline: stale entry {key} — the site is gone; remove "
              "the entry (python -m tools.lint --baseline-update)")
    for key in report.unjustified_keys:
        print(f"baseline: entry {key} has no justification — defend it "
              "in tools/lint/baseline.json or fix the violation")
    if not report.ok:
        print(f"\n{len(report.violations)} violation(s), "
              f"{len(report.stale_keys)} stale and "
              f"{len(report.unjustified_keys)} unjustified baseline "
              "entr(ies)")
        return 1
    print(f"tpu-lint: clean ({len(report.suppressed)} baselined "
          "finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
