"""tpu-lint: the repo's rule-plugin static-analysis framework.

The north star is a lockstep-vmapped symbolic EVM whose host (LASER-style)
and device (lockstep) paths must never diverge semantically, and whose hot
paths must never silently fall off the device. The invariants that keep
that true live here as pluggable AST rules — the same shape as the
detector modules under ``mythril_tpu/plugin/`` (a discovery singleton over
a rules package), applied to the source tree instead of the state space.

Rules (see ``tools/lint/rules/``):

* **R1 silent-excepts** — no silent blanket ``except`` swallows in the
  solver/device stack.
* **R2 dispatch-bypass** — no direct device-solver calls around the
  batched dispatch layer.
* **R3 trace-safety** — no implicit host↔device syncs or Python-side
  branching on traced values inside jit/vmap hot paths, and every
  *explicit* host sync site in ``mythril_tpu/parallel/`` must carry a
  baseline justification proving it is a deliberate bulk transfer.
* **R4 opcode-semantics** — the ``ops/opcodes.py`` table, the lockstep and
  symstep interpreters, and the host instruction handlers must agree:
  byte-complete dispatch parity and stack-effect consistency.
* **R5 env-knobs** — every ``MYTHRIL_TPU_*`` env read must be declared in
  the ``mythril_tpu/support/tpu_config.py`` registry, and the README knob
  table must match the registry rendering.
* **R6 metrics-registry** — every metric emitted through
  ``observe.metrics`` (``inc`` / ``set_gauge`` / ``observe``) must name a
  metric declared in ``mythril_tpu/observe/metrics.py``.
* **R7 jump-resolution** — jump-target resolution (JUMPDEST set
  construction, ``valid_jump_destinations``) belongs to
  ``mythril_tpu/staticanalysis/``; consumers read the CFA tables through
  ``smt/solver/cfa_screen.py``.
* **R8 hook-parity** — detection-module ``pre_hooks`` / ``post_hooks``
  must name declared opcodes (``ops/opcodes.py``), and hooked modules
  must declare a ``taint_sinks`` table consistent with their hook lists
  (the taint module screen's skip contract).
* **R9 abstract-domains** — value-range / stack-shape static reasoning
  (PUSH-immediate folds, stack-height simulation, ad-hoc interval
  domains) belongs to ``mythril_tpu/staticanalysis/``; consumers read
  the absint verdicts through ``smt/solver/cfa_screen.py``.
* **R10 gas-parity** — the superoptimizer's static gas table
  (``mythril_tpu/superopt/gas.py``) must stay in parity with the
  ``ops/opcodes.py`` schedule minimums: equal mnemonic sets, equal
  floor costs — so rewrite ranking can never drift from the
  interpreter's gas accounting.

Run ``python -m tools.lint`` (exit 1 on violations), or via the tier-1
suite (tests/test_lint.py). Known, audited violations live in
``tools/lint/baseline.json`` keyed by a stable fingerprint; every entry
carries a justification, stale entries fail the lint, and
``--baseline-update`` makes intentional growth an explicit diff.
"""

from __future__ import annotations

import ast
import importlib
import json
import os
import pkgutil
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(__file__), "baseline.json")


class Violation:
    """One finding: (rule code, repo-relative path, line, detail).

    ``where`` names the enclosing context (function name or a site tag);
    ``key`` is the stable baseline fingerprint — deliberately line-number
    free so unrelated edits above a site don't invalidate its entry.
    """

    __slots__ = ("rule", "path", "lineno", "where", "detail", "key")

    def __init__(self, rule: str, path: str, lineno: int, detail: str,
                 where: Optional[str] = None, key: Optional[str] = None):
        self.rule = rule
        self.path = path
        self.lineno = lineno
        self.where = where or "<module>"
        self.detail = detail
        self.key = key or f"{rule}:{path}:{self.where}"

    def as_tuple(self) -> Tuple[str, int, str]:
        """Legacy (relpath, lineno, detail) shape (check_excepts API)."""
        return (self.path, self.lineno, self.detail)

    def as_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "lineno": self.lineno,
                "where": self.where, "detail": self.detail, "key": self.key}

    def __repr__(self) -> str:
        return f"Violation({self.rule} {self.path}:{self.lineno} {self.detail!r})"


class LintContext:
    """Shared parse cache + tree-walking helpers handed to every rule."""

    def __init__(self, repo_root: str = REPO_ROOT):
        self.repo_root = repo_root
        self._trees: Dict[str, ast.AST] = {}
        self._sources: Dict[str, str] = {}

    def relpath(self, path: str) -> str:
        return os.path.relpath(path, self.repo_root).replace(os.sep, "/")

    def source(self, path: str) -> str:
        relpath = self.relpath(path)
        if relpath not in self._sources:
            with open(os.path.join(self.repo_root, relpath),
                      encoding="utf-8") as handle:
                self._sources[relpath] = handle.read()
        return self._sources[relpath]

    def tree(self, path: str) -> ast.AST:
        relpath = self.relpath(path)
        if relpath not in self._trees:
            self._trees[relpath] = ast.parse(
                self.source(relpath), filename=relpath)
        return self._trees[relpath]

    def iter_py(self, *scan_dirs: str) -> Iterator[str]:
        """Absolute paths of every .py file under the repo-relative dirs."""
        for scan_dir in scan_dirs:
            base = os.path.join(self.repo_root, scan_dir)
            if os.path.isfile(base) and base.endswith(".py"):
                yield base
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d != "__pycache__"]
                for filename in sorted(filenames):
                    if filename.endswith(".py"):
                        yield os.path.join(dirpath, filename)


class LintRule:
    """Base class for lint rules — mirrors plugin/interface.MythrilPlugin:
    subclasses carry their metadata as class attributes and are picked up
    by RuleDiscovery from the ``tools.lint.rules`` package."""

    code: str = "R?"                #: short id used by --rule and baselines
    name: str = "unnamed-rule"      #: kebab-case rule name
    description: str = ""           #: one-liner for --list-rules
    default_enabled: bool = True

    def run(self, ctx: LintContext) -> List[Violation]:
        raise NotImplementedError

    def check_paths(self, ctx: LintContext,
                    paths: Sequence[str]) -> List[Violation]:
        """File-scoped variant of run() over explicit paths (fixtures,
        pre-commit hooks). Rules whose checks are repo-structural rather
        than per-file (e.g. R4's dispatch-coverage direction) contribute
        only their per-file direction here."""
        return []


class RuleDiscovery:
    """Singleton that discovers LintRule subclasses in ``tools.lint.rules``
    (same shape as plugin/discovery.PluginDiscovery, with the package
    itself standing in for the entry-point group)."""

    _instance: Optional["RuleDiscovery"] = None

    def __new__(cls) -> "RuleDiscovery":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._rules = None
        return cls._instance

    def _discover(self) -> Dict[str, type]:
        from . import rules as rules_pkg

        found: Dict[str, type] = {}
        for info in sorted(pkgutil.iter_modules(rules_pkg.__path__),
                           key=lambda info: info.name):
            module = importlib.import_module(
                f"{rules_pkg.__name__}.{info.name}")
            for obj in vars(module).values():
                if (isinstance(obj, type) and issubclass(obj, LintRule)
                        and obj is not LintRule
                        and obj.__module__ == module.__name__):
                    found[obj.code] = obj
        return dict(sorted(found.items()))

    @property
    def installed_rules(self) -> Dict[str, type]:
        if self._rules is None:
            self._rules = self._discover()
        return self._rules

    def build_rule(self, code: str) -> LintRule:
        return self.installed_rules[code]()

    def get_rules(self, codes: Optional[Sequence[str]] = None
                  ) -> List[LintRule]:
        installed = self.installed_rules
        if codes is None:
            return [cls() for cls in installed.values()
                    if cls.default_enabled]
        unknown = [code for code in codes if code not in installed]
        if unknown:
            raise KeyError(
                f"unknown rule(s) {unknown}; installed: "
                f"{sorted(installed)}")
        return [installed[code]() for code in codes]


# -- baseline ------------------------------------------------------------------------

class Baseline:
    """Audited-violation allowlist: {key: justification}. Every entry MUST
    carry a non-empty justification (an entry created by --baseline-update
    starts as UNJUSTIFIED and fails the lint until a human writes one),
    and entries that no longer match a live violation fail as stale — a
    dead key would let a future regression sneak in under it."""

    UNJUSTIFIED = "UNJUSTIFIED: new entry — write a real justification"

    def __init__(self, entries: Optional[Dict[str, str]] = None,
                 path: Optional[str] = None):
        self.entries = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls({}, path)
        with open(path, encoding="utf-8") as handle:
            data = json.load(handle)
        entries = {entry["key"]: entry.get("justification", "")
                   for entry in data.get("entries", [])}
        return cls(entries, path)

    def save(self, path: Optional[str] = None) -> None:
        target = path or self.path
        data = {
            "_comment": (
                "tpu-lint baseline: audited violations keyed by stable "
                "fingerprint. Add entries only via "
                "`python -m tools.lint --baseline-update`, then replace "
                "the UNJUSTIFIED placeholder with a real defense."),
            "entries": [
                {"key": key, "justification": justification}
                for key, justification in sorted(self.entries.items())
            ],
        }
        with open(target, "w", encoding="utf-8") as handle:
            json.dump(data, handle, indent=2)
            handle.write("\n")

    def update_from(self, violations: Sequence[Violation]) -> None:
        """--baseline-update: keep justifications for keys that still fire,
        drop stale keys, add UNJUSTIFIED placeholders for new ones."""
        live = {v.key for v in violations}
        self.entries = {
            key: self.entries.get(key, self.UNJUSTIFIED) for key in live}


class LintReport:
    """Outcome of a lint run: active violations plus baseline hygiene
    failures (stale or unjustified entries)."""

    def __init__(self, violations: List[Violation],
                 suppressed: List[Violation],
                 stale_keys: List[str], unjustified_keys: List[str]):
        self.violations = violations
        self.suppressed = suppressed
        self.stale_keys = stale_keys
        self.unjustified_keys = unjustified_keys

    @property
    def ok(self) -> bool:
        return not (self.violations or self.stale_keys
                    or self.unjustified_keys)


def run_rules(rules: Sequence[LintRule],
              ctx: Optional[LintContext] = None) -> List[Violation]:
    ctx = ctx or LintContext()
    violations: List[Violation] = []
    for rule in rules:
        violations.extend(rule.run(ctx))
    return violations


def run_lint(codes: Optional[Sequence[str]] = None,
             baseline_path: str = DEFAULT_BASELINE,
             ctx: Optional[LintContext] = None,
             paths: Optional[Sequence[str]] = None) -> LintReport:
    """Run the selected rules and fold in the baseline. This is the
    programmatic entry point the CLI and the tier-1 test share. With
    ``paths``, each rule's file-scoped checker runs over just those files
    (and baseline hygiene is skipped — a partial view can't judge
    staleness)."""
    rules = RuleDiscovery().get_rules(codes)
    ctx = ctx or LintContext()
    if paths is None:
        raw = run_rules(rules, ctx)
    else:
        raw = []
        for rule in rules:
            raw.extend(rule.check_paths(ctx, paths))
    baseline = Baseline.load(baseline_path)
    ran_codes = {rule.code for rule in rules} if paths is None else set()

    active, suppressed = [], []
    hit_keys = set()
    for violation in raw:
        if violation.key in baseline.entries:
            hit_keys.add(violation.key)
            suppressed.append(violation)
        else:
            active.append(violation)
    # baseline hygiene only for the rules that actually ran: a --rule R3
    # run must not flag R1's entries as stale
    scoped = {key for key in baseline.entries
              if key.split(":", 1)[0] in ran_codes}
    stale = sorted(scoped - hit_keys)
    unjustified = sorted(
        key for key in scoped & hit_keys
        if not baseline.entries[key].strip()
        or baseline.entries[key].startswith("UNJUSTIFIED"))
    return LintReport(active, suppressed, stale, unjustified)
