"""R10 — superoptimizer gas-table parity with the opcode schedule.

The superoptimizer ranks proven-equivalent rewrites by static gas saved
(``mythril_tpu/superopt/gas.py``); the interpreter's authoritative gas
schedule lives in ``mythril_tpu/ops/opcodes.py`` as each mnemonic's
``(min, max)`` tuple. If the two drift — an EVM fork bump edits one
table, a typo prices an opcode wrong, a new mnemonic lands in only one —
the superoptimizer silently mis-ranks or mis-credits rewrites while
every equivalence proof still passes. This rule freezes the contract:

* equal mnemonic sets (every declared opcode is priced, nothing extra),
* ``STATIC_GAS[name] == OPCODES[name][gas][0]`` — the minimum-schedule
  (warm-access / zero-expansion) floor — for every mnemonic.

The comparison itself is ``gas.parity_errors`` (the same helper
tests/test_superopt.py calls), so the rule, the unit test, and the cost
model can never disagree about what parity means. Both modules are
loaded standalone by file path (the R4 pattern) — stdlib only, never
drags jax in. In file-scoped mode any explicitly named module that
defines a top-level ``STATIC_GAS`` is checked as a gas table (the
fixture hook); files without one are ignored.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from typing import Dict, List, Tuple

from .. import REPO_ROOT, LintContext, LintRule, Violation

GAS_PATH = "mythril_tpu/superopt/gas.py"
OPCODES_PATH = "mythril_tpu/ops/opcodes.py"

TABLE_NAME = "STATIC_GAS"

#: the three shapes gas.parity_errors emits; used to recover the
#: offending mnemonic as the violation's stable ``where`` site
_ERROR_SHAPES = (
    re.compile(r"^missing from STATIC_GAS: (?P<name>\w+)$"),
    re.compile(r"^not an opcode: (?P<name>\w+)$"),
    re.compile(r"^(?P<name>\w+): STATIC_GAS says "),
)


def _load_module(relpath: str, alias: str):
    """Standalone file-path import (the R4 pattern): no package tree,
    no jax, no side effects beyond the module's own top level."""
    path = os.path.join(REPO_ROOT, relpath)
    spec = importlib.util.spec_from_file_location(alias, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def load_opcode_schedule() -> Tuple[Dict[str, dict], str]:
    """(OPCODES, gas key) straight from ops/opcodes.py."""
    module = _load_module(OPCODES_PATH, "_tpu_lint_r10_opcodes")
    return module.OPCODES, module.GAS


def _table_lineno(tree: ast.AST) -> int:
    """Line of the top-level STATIC_GAS definition (0 when absent)."""
    for node in getattr(tree, "body", []):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for target in targets:
            if isinstance(target, ast.Name) and target.id == TABLE_NAME:
                return node.lineno
    return 0


def _site(error: str) -> str:
    for shape in _ERROR_SHAPES:
        match = shape.match(error)
        if match:
            return match.group("name")
    return "<table>"


def check_gas_file(relpath: str, ctx: LintContext = None
                   ) -> List[Violation]:
    """Parity violations for one gas-table module — the shipped
    superopt/gas.py or a fixture defining its own STATIC_GAS — anchored
    at the table definition line."""
    ctx = ctx or LintContext()
    relpath = ctx.relpath(os.path.join(REPO_ROOT, relpath))
    opcodes, gas_key = load_opcode_schedule()
    gas = _load_module(GAS_PATH, "_tpu_lint_r10_gas")
    if relpath == GAS_PATH:
        table = gas.STATIC_GAS
    else:
        alias = "_tpu_lint_r10_target_" + re.sub(r"\W", "_", relpath)
        table = getattr(_load_module(relpath, alias), TABLE_NAME)
    lineno = _table_lineno(ctx.tree(os.path.join(REPO_ROOT, relpath)))
    violations = []
    for error in gas.parity_errors(opcodes, gas_key, table=table):
        violations.append(Violation(
            "R10", relpath, max(lineno, 1),
            f"gas-table parity with {OPCODES_PATH}: {error} — the "
            "superoptimizer's rewrite ranking must price exactly the "
            "declared opcodes at their minimum-schedule cost",
            where=_site(error)))
    return violations


def _defines_table(tree: ast.AST) -> bool:
    return _table_lineno(tree) > 0


class GasParityRule(LintRule):
    code = "R10"
    name = "gas-parity"
    description = ("the superoptimizer's static gas table "
                   "(superopt/gas.py) must stay in parity with the "
                   "ops/opcodes.py schedule minimums: equal mnemonic "
                   "sets, equal floor costs")

    def run(self, ctx: LintContext) -> List[Violation]:
        return check_gas_file(GAS_PATH, ctx)

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        violations: List[Violation] = []
        for path in paths:
            if _defines_table(ctx.tree(path)):
                violations.extend(check_gas_file(ctx.relpath(path), ctx))
        return violations
