"""R8 — hook parity: detection-module hooks name real opcodes and
declare their taint sinks.

Detection modules register themselves on the SVM by opcode name
(``pre_hooks`` / ``post_hooks``); the taint module screen
(``analysis/module_screen.py``) decides whether a module can run at all
by intersecting those names with the contract's reachable-opcode summary.
Both contracts fail silently when a hook name drifts from the
``ops/opcodes.py`` table: the SVM never fires the hook (the module just
stops detecting) and the screen treats the name as unreachable (the
module is skipped everywhere). This rule moves both failures to lint
time:

* every name in a class's ``pre_hooks`` / ``post_hooks`` must be a
  declared opcode in ``mythril_tpu/ops/opcodes.py`` (hook lists are
  resolved through module-level list constants and ``+``-concatenation,
  the two idioms the modules actually use);
* every class that hooks opcodes must declare ``taint_sinks`` as a dict
  literal whose keys are hooked opcodes and whose values are tuples of
  int operand indices (``()`` = presence-only) — the screen's skip
  decisions are only sound when the sink table and the hook lists agree.

Hook lists this rule cannot resolve statically (computed at runtime)
are skipped, not flagged — the rule under-approximates rather than
guessing.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional, Set

from .. import REPO_ROOT, LintContext, LintRule, Violation

OPCODES_PATH = "mythril_tpu/ops/opcodes.py"
SCAN_DIRS = ("mythril_tpu", "tools", "tests", "bench.py")

HOOK_ATTRS = ("pre_hooks", "post_hooks")
SINK_ATTR = "taint_sinks"


def load_opcode_names() -> Set[str]:
    """Declared opcode names, loaded straight from ops/opcodes.py by
    file path (stdlib-only module; never drags jax in)."""
    path = os.path.join(REPO_ROOT, OPCODES_PATH)
    spec = importlib.util.spec_from_file_location(
        "_tpu_lint_ops_opcodes", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return set(module.OPCODES)


def _module_list_env(tree: ast.AST) -> Dict[str, List[str]]:
    """Module-level ``NAME = ["A", "B"]`` string-list constants — the
    indirection idiom hook lists use (e.g. ``CALL_LIST``)."""
    env: Dict[str, List[str]] = {}
    for node in getattr(tree, "body", []):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        resolved = _resolve_str_list(node.value, env)
        if resolved is not None:
            env[target.id] = resolved
    return env


def _resolve_str_list(node: ast.AST,
                      env: Dict[str, List[str]]) -> Optional[List[str]]:
    """A list of string constants out of a list literal, a known
    module-level name, or a ``+`` of resolvable parts; None when any
    piece is not statically known."""
    if isinstance(node, (ast.List, ast.Tuple)):
        out: List[str] = []
        for element in node.elts:
            if isinstance(element, ast.Constant) \
                    and isinstance(element.value, str):
                out.append(element.value)
            else:
                return None
        return out
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_str_list(node.left, env)
        right = _resolve_str_list(node.right, env)
        if left is None or right is None:
            return None
        return left + right
    return None


def _class_assignments(classdef: ast.ClassDef) -> Dict[str, ast.AST]:
    """name -> value expression for the class-body assignments this rule
    reads (last assignment wins, matching runtime semantics)."""
    out: Dict[str, ast.AST] = {}
    for node in classdef.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.value
    return out


def check_file(relpath: str, tree: ast.AST,
               opcode_names: Set[str]) -> List[Violation]:
    env = _module_list_env(tree)
    violations: List[Violation] = []
    seen_tags: dict = {}

    def flag(lineno: int, detail: str, tag: str) -> None:
        ordinal = seen_tags.get(tag, 0)
        seen_tags[tag] = ordinal + 1
        if ordinal:
            tag = f"{tag}#{ordinal}"
        violations.append(Violation(
            "R8", relpath, lineno, detail,
            where=tag, key=f"R8:{relpath}:{tag}"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        assigns = _class_assignments(node)
        hooks: Set[str] = set()
        resolvable = True
        for attr in HOOK_ATTRS:
            if attr not in assigns:
                continue
            resolved = _resolve_str_list(assigns[attr], env)
            if resolved is None:
                resolvable = False
                continue
            for name in resolved:
                hooks.add(name)
                if name not in opcode_names:
                    flag(assigns[attr].lineno,
                         f"{node.name}.{attr} hooks {name!r}, which is "
                         "not a declared opcode in "
                         f"{OPCODES_PATH} — the SVM will never fire "
                         "this hook and the taint module screen will "
                         "treat it as unreachable", name)
        if not hooks:
            # hookless class, empty hook lists (the base), or a hook
            # list the rule cannot resolve — under-approximate
            continue

        if SINK_ATTR not in assigns:
            flag(node.lineno,
                 f"{node.name} hooks opcodes but declares no "
                 f"`{SINK_ATTR}` — the taint module screen "
                 "(analysis/module_screen.py) needs the sink table to "
                 "decide skips soundly; declare `{\"OP\": ()}` entries "
                 "(empty tuple = presence-only)",
                 f"{node.name}:taint-sinks")
            continue
        sinks = assigns[SINK_ATTR]
        if not isinstance(sinks, ast.Dict):
            flag(sinks.lineno,
                 f"{node.name}.{SINK_ATTR} must be a dict literal "
                 "(opcode -> tuple of operand indices) so the screen's "
                 "contract is statically auditable",
                 f"{node.name}:taint-sinks")
            continue
        for key_node, value_node in zip(sinks.keys, sinks.values):
            if not (isinstance(key_node, ast.Constant)
                    and isinstance(key_node.value, str)):
                flag(sinks.lineno,
                     f"{node.name}.{SINK_ATTR} has a non-string-literal "
                     "key — sink opcodes must be spelled out",
                     f"{node.name}:taint-sinks")
                continue
            key = key_node.value
            if key not in opcode_names:
                flag(key_node.lineno,
                     f"{node.name}.{SINK_ATTR} names {key!r}, which is "
                     f"not a declared opcode in {OPCODES_PATH}",
                     f"{node.name}:{key}")
            elif resolvable and key not in hooks:
                flag(key_node.lineno,
                     f"{node.name}.{SINK_ATTR} names {key!r}, which is "
                     "not among the class's pre/post hooks — the screen "
                     "only consults sinks at hooked sites, so this "
                     "entry is dead (typo or stale hook list)",
                     f"{node.name}:{key}")
            ok_value = isinstance(value_node, ast.Tuple) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in value_node.elts)
            if not ok_value:
                flag(value_node.lineno,
                     f"{node.name}.{SINK_ATTR}[{key!r}] must be a tuple "
                     "of int operand indices (() = presence-only)",
                     f"{node.name}:{key}:value")
    return violations


class HookParityRule(LintRule):
    code = "R8"
    name = "hook-parity"
    description = ("detection-module pre/post hooks must name declared "
                   "opcodes (ops/opcodes.py) and hooked modules must "
                   "declare a consistent taint_sinks table")

    def run(self, ctx: LintContext) -> List[Violation]:
        opcode_names = load_opcode_names()
        violations: List[Violation] = []
        for path in ctx.iter_py(*SCAN_DIRS):
            relpath = ctx.relpath(path)
            if relpath.startswith("tools/lint/") \
                    or relpath == "tools/check_excepts.py" \
                    or relpath.startswith("tests/data/lint/"):
                continue
            violations.extend(
                check_file(relpath, ctx.tree(path), opcode_names))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        opcode_names = load_opcode_names()
        violations: List[Violation] = []
        for path in paths:
            violations.extend(
                check_file(ctx.relpath(path), ctx.tree(path),
                           opcode_names))
        return violations
