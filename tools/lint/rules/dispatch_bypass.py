"""R2 — no device-solver calls that bypass the batched dispatch layer.

Scans all of `mythril_tpu/` for calls to `solve_cnf_device` /
`solve_cnf_device_batch` outside smt/solver/dispatch.py (the batching
queue that owns the resilience contract: one breaker fire per batch,
verdict caching, crosscheck sampling) and parallel/jax_solver.py (the
implementation itself). A direct call skips the circuit breaker, the
verdict cache, and the batch statistics — every caller must go through
`dispatch.submit()`/`dispatch.solve()`.
"""

from __future__ import annotations

import ast
from typing import List

from .. import LintContext, LintRule, Violation

#: device-solver entry points that must only be reached via the dispatch queue
DEVICE_ENTRYPOINTS = ("solve_cnf_device", "solve_cnf_device_batch")

#: the only files allowed to call DEVICE_ENTRYPOINTS directly (repo-relative)
DEVICE_CALLERS = {
    "mythril_tpu/smt/solver/dispatch.py",
    "mythril_tpu/parallel/jax_solver.py",
}

#: scan root: the whole package
SCAN_DIR = "mythril_tpu"


def check_file(relpath: str, tree: ast.AST) -> List[Violation]:
    """Direct `solve_cnf_device[_batch](...)` calls in one parsed file.
    References that are not calls (imports, monkeypatch targets) pass."""
    if relpath in DEVICE_CALLERS:
        return []
    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name not in DEVICE_ENTRYPOINTS:
            continue
        violations.append(Violation(
            "R2", relpath, node.lineno,
            f"direct {name}() call bypasses the batched dispatch layer "
            "(breaker, verdict cache, crosscheck sampling) — go through "
            "smt/solver/dispatch.submit()/solve() instead",
            where=name))
    return violations


class DispatchBypassRule(LintRule):
    code = "R2"
    name = "dispatch-bypass"
    description = ("no direct solve_cnf_device[_batch]() calls outside "
                   "smt/solver/dispatch.py and parallel/jax_solver.py")

    def run(self, ctx: LintContext) -> List[Violation]:
        violations = []
        for path in ctx.iter_py(SCAN_DIR):
            violations.extend(check_file(ctx.relpath(path), ctx.tree(path)))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        violations = []
        for path in paths:
            violations.extend(check_file(ctx.relpath(path), ctx.tree(path)))
        return violations
