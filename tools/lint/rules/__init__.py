"""tpu-lint rule modules. Every module here that defines a LintRule
subclass is auto-discovered by tools.lint.RuleDiscovery — add a rule by
dropping a new module in this package (see README "Static analysis")."""
