"""R4 — opcode-semantics consistency between the table, the device
interpreters, and the host handlers.

The ``ops/opcodes.py`` table is the single source of truth: the lockstep
interpreter densifies it into POPS/PUSHES/GAS/VALID arrays, and the host
LASER engine dispatches ``core/instructions.py`` handlers by mnemonic.
Those three views drift independently — a mnemonic typo in ``is_op("...")``
compiles fine and silently never matches; a new table opcode with no
dispatch silently escapes or errors; a handler whose stack effect differs
from the table is host-vs-lockstep divergence the Z3 oracle only sees as
an unexplained mismatch much later. This rule proves, statically:

* **refs-exist**: every mnemonic the interpreters reference — via
  ``is_op("NAME")`` / ``op_in(...)`` arguments, ``O["NAME"]`` subscripts,
  or the string lists driving the table-densification ``for`` loops —
  exists in the opcode table;
* **byte-complete dispatch**: every byte in the table is either
  referenced by mnemonic, covered by a decode byte-range
  (``(op >= 0x5F) & (op <= 0x7F)`` / ``range(0x5F, 0xA0)``), or named in
  lockstep's explicit ``UNIMPLEMENTED_OPS`` list;
* **host parity**: every table mnemonic has a ``core/instructions.py``
  handler (``add_``, generic ``push_``/``dup_``/``swap_``/``log_`` for
  the generated families), and each handler's statically countable stack
  effect (``mstate.pop(n)`` / ``stack.append``) matches the table's
  POPS/PUSHES entry — data-dependent handlers are skipped explicitly in
  ``STACK_CHECK_SKIP`` with a justification.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from typing import Dict, List, Set, Tuple

from .. import REPO_ROOT, LintContext, LintRule, Violation

OPCODES_PATH = "mythril_tpu/ops/opcodes.py"
INTERPRETERS = ("mythril_tpu/parallel/lockstep.py",
                "mythril_tpu/parallel/symstep.py")
HANDLERS_PATH = "mythril_tpu/core/instructions.py"

#: handlers whose stack effect is data-dependent or branch-duplicated in a
#: way a static pop/append count cannot follow. Each entry defends itself;
#: removing an entry is safe (the check simply starts running).
STACK_CHECK_SKIP = {
    # generic family handlers: the instruction byte decides n
    "push_", "push0_", "dup_", "swap_", "log_",
    # delegate to a shared call/create implementation; stack effect is
    # applied inside the delegate across world-state forks
    "call_", "callcode_", "delegatecall_", "staticcall_",
    "create_", "create2_",
    # halting/forking semantics: jumpi_ forks both sides structurally,
    # return_/revert_/stop_/selfdestruct_ end the state instead of pushing
    "jumpi_", "return_", "revert_", "stop_", "selfdestruct_", "invalid_",
}

_FAMILY = re.compile(r"^(PUSH|DUP|SWAP|LOG)(\d+)$")


def load_opcode_table() -> Dict[str, Tuple[int, int, int]]:
    """{mnemonic: (byte, pops, pushes)} loaded straight from
    ops/opcodes.py by file path — the module is stdlib-only, so this
    never drags jax in."""
    path = os.path.join(REPO_ROOT, OPCODES_PATH)
    spec = importlib.util.spec_from_file_location("_tpu_lint_opcodes", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return {
        name: (meta[module.ADDRESS],
               meta[module.STACK][0], meta[module.STACK][1])
        for name, meta in module.OPCODES.items()
    }


# -- interpreter-side collection -------------------------------------------------


def _const_str(node: ast.AST) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return ""


def collect_mnemonic_refs(tree: ast.AST) -> Dict[str, int]:
    """{mnemonic: first lineno} for every opcode-table reference: is_op/
    op_in string arguments, O["..."] subscripts, and string constants in
    the list/tuple literals that drive table-densification for-loops."""
    refs: Dict[str, int] = {}

    def add(name: str, lineno: int) -> None:
        if name:
            refs.setdefault(name, lineno)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            name = func.id if isinstance(func, ast.Name) else (
                func.attr if isinstance(func, ast.Attribute) else None)
            if name in ("is_op", "op_in"):
                for arg in node.args:
                    add(_const_str(arg), node.lineno)
        elif isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name) and node.value.id == "O":
                sl = node.slice
                if isinstance(sl, ast.Index):  # pragma: no cover (py<3.9)
                    sl = sl.value
                add(_const_str(sl), node.lineno)
        elif isinstance(node, ast.For) \
                and isinstance(node.iter, (ast.List, ast.Tuple)):
            for item in ast.walk(node.iter):
                add(_const_str(item), node.lineno)
    return refs


def collect_byte_intervals(tree: ast.AST) -> List[Tuple[int, int]]:
    """Inclusive [lo, hi] opcode-byte ranges the interpreters decode
    wholesale: `(op >= 0x5F) & (op <= 0x7F)` masks and
    `for _byte in range(0x5F, 0xA0)` densification loops. Only the
    generated-family region (0x5F..0x9F) is accepted from range() loops,
    so unrelated small loops can't fake dispatch coverage."""
    intervals: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitAnd):
            lo = _compare_bound(node.left, ("Gt", "GtE"))
            hi = _compare_bound(node.right, ("Lt", "LtE"))
            if lo is not None and hi is not None:
                intervals.append((lo, hi))
        elif isinstance(node, ast.For) and isinstance(node.iter, ast.Call):
            func = node.iter.func
            if isinstance(func, ast.Name) and func.id == "range" \
                    and len(node.iter.args) == 2:
                args = node.iter.args
                if all(isinstance(a, ast.Constant)
                       and isinstance(a.value, int) for a in args):
                    lo, hi = args[0].value, args[1].value - 1
                    if 0x5F <= lo <= hi <= 0x9F:
                        intervals.append((lo, hi))
    return intervals


def _compare_bound(node: ast.AST, ops: Tuple[str, ...]):
    """`op >= 0x5F` -> 0x5F (adjusted to inclusive), else None."""
    if not (isinstance(node, ast.Compare) and len(node.ops) == 1
            and isinstance(node.left, ast.Name) and node.left.id == "op"
            and isinstance(node.comparators[0], ast.Constant)
            and isinstance(node.comparators[0].value, int)):
        return None
    kind = type(node.ops[0]).__name__
    value = node.comparators[0].value
    if kind not in ops:
        return None
    if kind == "Gt":
        value += 1
    elif kind == "Lt":
        value -= 1
    return value


def collect_unimplemented(tree: ast.AST) -> Set[str]:
    """Mnemonics in an `UNIMPLEMENTED_OPS = [...]` module-level list —
    the explicit "the device does not dispatch this" declaration."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if "UNIMPLEMENTED_OPS" in targets \
                    and isinstance(node.value, (ast.List, ast.Tuple)):
                for item in node.value.elts:
                    if _const_str(item):
                        names.add(_const_str(item))
    return names


def check_interpreter_file(relpath: str, tree: ast.AST,
                           table: Dict[str, Tuple[int, int, int]]
                           ) -> List[Violation]:
    """refs-exist direction, per file (fixture-testable standalone)."""
    violations = []
    for name, lineno in sorted(collect_mnemonic_refs(tree).items()):
        if name not in table:
            violations.append(Violation(
                "R4", relpath, lineno,
                f"interpreter references unknown mnemonic {name!r} — not "
                "in ops/opcodes.py, so the comparison can never match",
                where=name, key=f"R4:{relpath}:ref:{name}"))
    return violations


# -- host-handler side -----------------------------------------------------------


def handler_name_for(mnemonic: str) -> str:
    family = _FAMILY.match(mnemonic)
    if mnemonic == "PUSH0":
        return "push0_"
    if family:
        return family.group(1).lower() + "_"
    if mnemonic == "DIFFICULTY":  # pre-Merge alias for the same byte
        return "prevrandao_"
    return mnemonic.lower() + "_"


def handler_stack_effect(fn: ast.AST) -> Tuple[int, int]:
    """(pops, appends) statically counted from mstate.pop(n)/stack.pop()
    and stack.append(...) calls."""
    pops = appends = 0
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr == "pop":
            owner = node.func.value
            if isinstance(owner, ast.Attribute) \
                    and owner.attr in ("mstate", "stack"):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, int):
                    pops += node.args[0].value
                else:
                    pops += 1
        elif node.func.attr == "append":
            owner = node.func.value
            if isinstance(owner, ast.Attribute) and owner.attr == "stack":
                appends += 1
    return pops, appends


class OpcodeSemanticsRule(LintRule):
    code = "R4"
    name = "opcode-semantics"
    description = ("opcodes.py table, lockstep/symstep dispatch, and host "
                   "instruction handlers must agree: byte-complete parity "
                   "and consistent stack effects")

    def run(self, ctx: LintContext) -> List[Violation]:
        table = load_opcode_table()
        violations: List[Violation] = []

        refs: Dict[str, int] = {}
        intervals: List[Tuple[int, int]] = []
        unimplemented: Set[str] = set()
        for relpath in INTERPRETERS:
            tree = ctx.tree(os.path.join(ctx.repo_root, relpath))
            violations.extend(check_interpreter_file(relpath, tree, table))
            for name, lineno in collect_mnemonic_refs(tree).items():
                refs.setdefault(name, lineno)
            intervals.extend(collect_byte_intervals(tree))
            unimplemented |= collect_unimplemented(tree)

        for name in sorted(unimplemented):
            if name not in table:
                violations.append(Violation(
                    "R4", INTERPRETERS[0], 1,
                    f"UNIMPLEMENTED_OPS names unknown mnemonic {name!r}",
                    where=name, key=f"R4:unimplemented:{name}"))

        # byte-complete dispatch: dedupe aliases at the byte level
        # (DIFFICULTY shares 0x44 with PREVRANDAO)
        covered_bytes = {table[name][0] for name in refs if name in table}
        covered_bytes |= {table[name][0] for name in unimplemented
                         if name in table}
        for lo, hi in intervals:
            covered_bytes |= set(range(lo, hi + 1))
        for name, (byte, _, _) in sorted(table.items()):
            if byte not in covered_bytes:
                violations.append(Violation(
                    "R4", INTERPRETERS[0], 1,
                    f"table opcode {name} (0x{byte:02X}) is neither "
                    "dispatched by lockstep/symstep nor named in "
                    "UNIMPLEMENTED_OPS — lanes hitting it fall into "
                    "undefined behavior",
                    where=name, key=f"R4:dispatch:{name}"))

        violations.extend(self._check_handlers(ctx, table))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        # only the refs-exist direction is per-file; dispatch coverage and
        # handler stack effects are properties of the whole tree
        table = load_opcode_table()
        violations: List[Violation] = []
        for path in paths:
            violations.extend(check_interpreter_file(
                ctx.relpath(path), ctx.tree(path), table))
        return violations

    def _check_handlers(self, ctx: LintContext,
                        table: Dict[str, Tuple[int, int, int]]
                        ) -> List[Violation]:
        relpath = HANDLERS_PATH
        tree = ctx.tree(os.path.join(ctx.repo_root, relpath))
        handlers: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name.endswith("_") \
                    and not node.name.startswith("_"):
                handlers[node.name] = node

        violations = []
        for mnemonic, (byte, pops, pushes) in sorted(table.items()):
            handler = handler_name_for(mnemonic)
            fn = handlers.get(handler)
            if fn is None:
                violations.append(Violation(
                    "R4", relpath, 1,
                    f"no host handler {handler}() for table opcode "
                    f"{mnemonic} (0x{byte:02X}) — the host engine raises "
                    "InvalidInstruction where the device executes it",
                    where=mnemonic, key=f"R4:handler:{mnemonic}"))
                continue
            if handler in STACK_CHECK_SKIP:
                continue
            counted_pops, counted_appends = handler_stack_effect(fn)
            if counted_pops != pops:
                violations.append(Violation(
                    "R4", relpath, fn.lineno,
                    f"{handler}() pops {counted_pops} but the table says "
                    f"{mnemonic} pops {pops} — host-vs-lockstep stack "
                    "drift (lockstep densifies POPS from the table)",
                    where=mnemonic, key=f"R4:pops:{mnemonic}"))
            if (pushes == 0) != (counted_appends == 0):
                violations.append(Violation(
                    "R4", relpath, fn.lineno,
                    f"{handler}() appends {counted_appends} result(s) but "
                    f"the table says {mnemonic} pushes {pushes} — "
                    "host-vs-lockstep stack drift",
                    where=mnemonic, key=f"R4:pushes:{mnemonic}"))
        return violations
