"""R1 — no silent blanket exception swallows in the solver/device stack.

Scans `mythril_tpu/smt/` and `mythril_tpu/parallel/` for `except` handlers
that are BOTH broad (bare `except:`, `except Exception:`, or
`except BaseException:`) AND silent (a body of only `pass`/`continue`/
`...`). A handler like that erases the entire failure story the resilience
subsystem exists to tell (support/resilience.py: every backend failure must
be classified, logged, and counted) — it is exactly the pattern ISSUE 2
replaced at smt/solver/solver.py:48.

Audited survivors live in tools/lint/baseline.json keyed
``R1:<file>:<enclosing def>`` — e.g. a ``__del__`` finalizer, where raising
during interpreter teardown is worse than any leak. Add an entry only via
``--baseline-update`` plus a written justification.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .. import LintContext, LintRule, Violation

#: directories whose every .py file is linted (repo-relative)
SCAN_DIRS = ("mythril_tpu/smt", "mythril_tpu/parallel")

_BROAD = ("Exception", "BaseException")


def is_broad(handler: ast.ExceptHandler) -> bool:
    node = handler.type
    if node is None:
        return True
    if isinstance(node, ast.Name):
        return node.id in _BROAD
    if isinstance(node, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in node.elts)
    return False


def is_silent(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue)
               or (isinstance(stmt, ast.Expr)
                   and isinstance(stmt.value, ast.Constant)
                   and stmt.value.value is Ellipsis)
               for stmt in handler.body)


def enclosing_function(tree: ast.AST, target: ast.AST) -> Optional[str]:
    """Name of the innermost def/async def containing `target` (module
    level -> None)."""
    found: List[Optional[str]] = [None]

    def descend(node: ast.AST, current: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if child is target:
                found[0] = current
                return
            name = current
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            descend(child, name)

    descend(tree, None)
    return found[0]


def check_file(relpath: str, tree: ast.AST) -> List[Violation]:
    """All silent blanket excepts in one parsed file (no allowlisting —
    suppression is the framework baseline's job)."""
    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not (is_broad(node) and is_silent(node)):
            continue
        function = enclosing_function(tree, node)
        where = function or "<module>"
        violations.append(Violation(
            "R1", relpath, node.lineno,
            f"silent blanket except in {where}() — classify and log the "
            "failure (support/resilience.py) or narrow the except; "
            "baseline in tools/lint/baseline.json only with justification",
            where=where))
    return violations


class SilentExceptRule(LintRule):
    code = "R1"
    name = "silent-excepts"
    description = ("no silent blanket `except Exception: pass` swallows in "
                   "mythril_tpu/smt/ and mythril_tpu/parallel/")

    def run(self, ctx: LintContext) -> List[Violation]:
        violations = []
        for path in ctx.iter_py(*SCAN_DIRS):
            violations.extend(check_file(ctx.relpath(path), ctx.tree(path)))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        violations = []
        for path in paths:
            violations.extend(check_file(ctx.relpath(path), ctx.tree(path)))
        return violations
