"""R6 — metric hygiene: every emitted metric names a declared metric.

The observe metrics registry (``mythril_tpu/observe/metrics.py``) is the
single source of truth for metric names, kinds, units, and docs — exactly
as R5 makes ``tpu_config.py`` the source of truth for env knobs. An
emission of an undeclared name would raise ``KeyError`` at runtime, but
only on the code path that emits it; this rule moves that failure to lint
time, for every path, including the cold ones tests never walk.

Checked: every call to an emitter (``inc`` / ``set_gauge`` /
``observe``) or a reader (``value`` / ``set_value`` / ``histogram`` /
``labels`` / ``quantile`` — the exporter-side surface ISSUE 12 added) on
a module imported from ``mythril_tpu.observe`` (``metrics.inc(...)``, an
aliased ``from ... import metrics as m``, or a from-imported
``inc(...)``) whose first argument is a string literal must name a
metric in ``REGISTRY``. Dynamic names (loops over ``FACADE_METRICS``,
f-string families) are the registry's runtime ``KeyError`` contract's
problem, not this rule's.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import List, Set

from .. import REPO_ROOT, LintContext, LintRule, Violation

METRICS_PATH = "mythril_tpu/observe/metrics.py"
SCAN_DIRS = ("mythril_tpu", "tools", "tests", "bench.py")

#: emission calls whose first positional argument is a metric name
EMITTERS = ("inc", "set_gauge", "observe")

#: read-side calls (exporter, views, bench extras) audited the same way
READERS = ("value", "set_value", "histogram", "labels", "quantile")

AUDITED = EMITTERS + READERS


def load_registry() -> Set[str]:
    """Declared metric names, loaded straight from observe/metrics.py by
    file path (stdlib-only module; never drags jax in)."""
    path = os.path.join(REPO_ROOT, METRICS_PATH)
    spec = importlib.util.spec_from_file_location(
        "_tpu_lint_observe_metrics", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return set(module.REGISTRY)


def _metric_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the metrics MODULE: ``from x.observe import
    metrics [as m]`` and ``import mythril_tpu.observe.metrics as m``."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "observe"
                                or node.module.endswith(".observe")):
                for name in node.names:
                    if name.name == "metrics":
                        aliases.add(name.asname or name.name)
        elif isinstance(node, ast.Import):
            for name in node.names:
                if name.name.endswith(".observe.metrics"):
                    aliases.add(name.asname or name.name.split(".", 1)[0])
    return aliases


def _emitter_imports(tree: ast.AST) -> Set[str]:
    """Local names bound to emitter FUNCTIONS from the metrics module:
    ``from x.observe.metrics import inc [as bump]``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                (node.module == "metrics"
                 or node.module.endswith(".metrics")):
            for name in node.names:
                if name.name in AUDITED:
                    out.add(name.asname or name.name)
    return out


def check_file(relpath: str, tree: ast.AST,
               registry: Set[str]) -> List[Violation]:
    aliases = _metric_aliases(tree)
    emitters = _emitter_imports(tree)
    if not aliases and not emitters:
        return []
    violations: List[Violation] = []

    def check_call(node: ast.Call, how: str) -> None:
        if not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value not in registry:
            violations.append(Violation(
                "R6", relpath, node.lineno,
                f"{how} references undeclared metric {arg.value!r} — "
                "declare it in mythril_tpu/observe/metrics.py (name, "
                "kind, unit, docstring) or fix the typo; undeclared "
                "references raise KeyError at runtime",
                where=arg.value, key=f"R6:{relpath}:{arg.value}"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in AUDITED \
                and isinstance(func.value, ast.Name) \
                and func.value.id in aliases:
            check_call(node, f"{func.value.id}.{func.attr}")
        elif isinstance(func, ast.Name) and func.id in emitters:
            check_call(node, func.id)
    return violations


class MetricsRegistryRule(LintRule):
    code = "R6"
    name = "metrics-registry"
    description = ("every metric referenced via observe.metrics "
                   "emitters (inc/set_gauge/observe) or readers "
                   "(value/set_value/histogram/labels/quantile) must "
                   "be declared in mythril_tpu/observe/metrics.py")

    def run(self, ctx: LintContext) -> List[Violation]:
        registry = load_registry()
        violations: List[Violation] = []
        for path in ctx.iter_py(*SCAN_DIRS):
            relpath = ctx.relpath(path)
            if relpath.startswith("tools/lint/") \
                    or relpath == "tools/check_excepts.py" \
                    or relpath.startswith("tests/data/lint/"):
                continue  # the linter and its fixtures mention metrics freely
            violations.extend(
                check_file(relpath, ctx.tree(path), registry))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        registry = load_registry()
        violations: List[Violation] = []
        for path in paths:
            violations.extend(
                check_file(ctx.relpath(path), ctx.tree(path), registry))
        return violations
