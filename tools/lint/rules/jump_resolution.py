"""R7 — jump-resolution ownership: the CFA tables are the single source
of jump-target truth.

``mythril_tpu/staticanalysis/`` resolves jump targets once per contract
(reachability-refined JUMPDEST bitmap, per-site resolved target sets)
and every consumer reads those tables through
``smt/solver/cfa_screen.py``. A module that re-derives the target set —
building its own JUMPDEST collection or a ``valid_jump_destinations``
set — forks that truth: the copies drift the moment the cfa pass learns
something (dead-code refinement, new dataflow), and the screen's A/B
counters stop meaning anything.

Flagged outside ``mythril_tpu/staticanalysis/``:

* any assignment to a ``valid_jump_destinations`` name/attribute
  (the literal re-implementation this rule exists for);
* a set/list comprehension — or a generator fed straight into
  ``set()``/``list()``/``frozenset()``/``sorted()``/``tuple()`` — whose
  filter or element compares something to the string ``"JUMPDEST"``
  (collection-building from a JUMPDEST scan; point checks like
  ``op_code != "JUMPDEST"`` on one instruction, or ``next(...)``
  lookups, are fine and not flagged);
* a ``for`` loop whose body tests ``== "JUMPDEST"`` and then
  ``.add(...)``/``.append(...)``s into a collection (the longhand of
  the comprehension above).

The one legitimate producer — ``frontends/disassembler.py``, which
builds the *unrefined* bitmap the cfa pass itself starts from — carries
a justified baseline entry.
"""

from __future__ import annotations

import ast
from typing import List

from .. import LintContext, LintRule, Violation

SCAN_DIRS = ("mythril_tpu", "tools", "tests", "bench.py")
ALLOWED_PREFIX = "mythril_tpu/staticanalysis/"
SET_NAME = "valid_jump_destinations"
MARKER = "JUMPDEST"


def _compares_jumpdest(node: ast.AST) -> bool:
    """Any Compare under `node` with a "JUMPDEST" string operand."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Compare):
            continue
        operands = [sub.left] + list(sub.comparators)
        for operand in operands:
            if isinstance(operand, ast.Constant) \
                    and operand.value == MARKER:
                return True
    return False


def _comp_scans_jumpdest(node) -> bool:
    """Comprehension/generator whose element or filters compare to
    "JUMPDEST"."""
    clauses = [node.elt] + [
        cond for gen in node.generators for cond in gen.ifs]
    return any(_compares_jumpdest(clause) for clause in clauses)


def _target_names(node: ast.AST) -> List[str]:
    """Plain/attribute names an assignment writes to."""
    names = []
    for target in ast.walk(node):
        if isinstance(target, ast.Attribute):
            names.append(target.attr)
        elif isinstance(target, ast.Name):
            names.append(target.id)
    return names


def check_file(relpath: str, tree: ast.AST) -> List[Violation]:
    violations: List[Violation] = []

    seen_tags: dict = {}

    def flag(lineno: int, how: str, tag: str) -> None:
        # stable, line-free keys: same-kind repeats get an ordinal suffix
        # (walk order is deterministic for a given file)
        ordinal = seen_tags.get(tag, 0)
        seen_tags[tag] = ordinal + 1
        if ordinal:
            tag = f"{tag}#{ordinal}"
        violations.append(Violation(
            "R7", relpath, lineno,
            f"{how} re-implements jump-target resolution — consume the "
            "shared CFA tables instead (staticanalysis.get_cfa / "
            "smt/solver/cfa_screen.py: is_valid_target, "
            "resolved_jump_targets)",
            where=tag, key=f"R7:{relpath}:{tag}"))

    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for target in targets:
                if SET_NAME in _target_names(target):
                    flag(node.lineno,
                         f"assignment to `{SET_NAME}`", SET_NAME)
        elif isinstance(node, (ast.SetComp, ast.ListComp)):
            if _comp_scans_jumpdest(node):
                kind = type(node).__name__
                flag(node.lineno,
                     f"{kind} collecting instructions by "
                     f'`== "{MARKER}"`', f"comp:{kind}")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id in ("set", "list", "frozenset",
                                     "sorted", "tuple"):
            # a bare generator is often a point lookup (next(...)); it
            # only builds a collection when fed to a constructor
            for arg in node.args:
                if isinstance(arg, ast.GeneratorExp) \
                        and _comp_scans_jumpdest(arg):
                    flag(node.lineno,
                         f"{node.func.id}(generator) collecting "
                         f'instructions by `== "{MARKER}"`',
                         f"comp:{node.func.id}")
        elif isinstance(node, ast.For):
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.If)
                        and _compares_jumpdest(sub.test)):
                    continue
                for call in ast.walk(sub):
                    if isinstance(call, ast.Call) \
                            and isinstance(call.func, ast.Attribute) \
                            and call.func.attr in ("add", "append"):
                        flag(sub.lineno,
                             f'for-loop collecting `== "{MARKER}"` '
                             "instructions via "
                             f".{call.func.attr}()", "for-collect")
                        break
                else:
                    continue
                break
    return violations


class JumpResolutionRule(LintRule):
    code = "R7"
    name = "jump-resolution"
    description = ("jump-target resolution (JUMPDEST set construction) "
                   "belongs to staticanalysis/ — consumers read the CFA "
                   "tables via smt/solver/cfa_screen.py")

    def run(self, ctx: LintContext) -> List[Violation]:
        violations: List[Violation] = []
        for path in ctx.iter_py(*SCAN_DIRS):
            relpath = ctx.relpath(path)
            if relpath.startswith(ALLOWED_PREFIX) \
                    or relpath.startswith("tools/lint/") \
                    or relpath == "tools/check_excepts.py" \
                    or relpath.startswith("tests/data/lint/"):
                continue
            violations.extend(check_file(relpath, ctx.tree(path)))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        violations: List[Violation] = []
        for path in paths:
            violations.extend(
                check_file(ctx.relpath(path), ctx.tree(path)))
        return violations
