"""R5 — env-knob hygiene: every ``MYTHRIL_TPU_*`` read is declared.

An undeclared knob is invisible: it has no documented type or default, no
README entry, and a typo in its name silently reads the default forever.
This rule enforces the ``mythril_tpu/support/tpu_config.py`` registry as
the single source of truth:

* every ``os.environ.get/[]/pop/setdefault`` or ``os.getenv`` read of a
  ``MYTHRIL_TPU_*`` name — anywhere in ``mythril_tpu/``, ``tools/``,
  ``tests/``, or ``bench.py`` — must name a registered knob (writes via
  ``setdefault``/``[...] =`` are checked too: setting an undeclared knob
  is the same typo one step earlier);
* the README knob table between the ``<!-- knob-table:start -->`` /
  ``<!-- knob-table:end -->`` markers must byte-match
  ``tpu_config.render_markdown_table()`` — regenerate with
  ``python -m mythril_tpu.support.tpu_config``.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import List, Set

from .. import REPO_ROOT, LintContext, LintRule, Violation

TPU_CONFIG_PATH = "mythril_tpu/support/tpu_config.py"
SCAN_DIRS = ("mythril_tpu", "tools", "tests", "bench.py")
README_PATH = "README.md"
TABLE_START = "<!-- knob-table:start -->"
TABLE_END = "<!-- knob-table:end -->"

PREFIX = "MYTHRIL_TPU_"


def load_registry() -> Set[str]:
    """Declared knob names, loaded straight from tpu_config.py by file
    path (stdlib-only module; never drags jax in)."""
    path = os.path.join(REPO_ROOT, TPU_CONFIG_PATH)
    spec = importlib.util.spec_from_file_location(
        "_tpu_lint_tpu_config", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return set(module.REGISTRY)


def _render_table() -> str:
    path = os.path.join(REPO_ROOT, TPU_CONFIG_PATH)
    spec = importlib.util.spec_from_file_location(
        "_tpu_lint_tpu_config_render", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module.render_markdown_table()


def _env_name(node: ast.AST) -> str:
    """The MYTHRIL_TPU_* string literal named by an environ access node
    argument, or ''."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith(PREFIX):
        return node.value
    return ""


def _is_environ(node: ast.AST) -> bool:
    """`os.environ` / bare `environ`."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def check_file(relpath: str, tree: ast.AST,
               registry: Set[str]) -> List[Violation]:
    violations = []

    def check_name(name: str, lineno: int, how: str) -> None:
        if name and name not in registry:
            violations.append(Violation(
                "R5", relpath, lineno,
                f"{how} of undeclared knob {name} — declare it in "
                "mythril_tpu/support/tpu_config.py (name, type, default, "
                "docstring) so the README table and the runtime accessors "
                "know it exists",
                where=name, key=f"R5:{relpath}:{name}"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and _is_environ(func.value) \
                    and func.attr in ("get", "pop", "setdefault"):
                if node.args:
                    check_name(_env_name(node.args[0]), node.lineno,
                               f"os.environ.{func.attr}")
            elif isinstance(func, ast.Attribute) \
                    and func.attr == "getenv":
                if node.args:
                    check_name(_env_name(node.args[0]), node.lineno,
                               "os.getenv")
        elif isinstance(node, ast.Subscript) \
                and _is_environ(node.value):
            sl = node.slice
            if isinstance(sl, ast.Index):  # pragma: no cover (py<3.9)
                sl = sl.value
            check_name(_env_name(sl), node.lineno, "os.environ[...]")
    return violations


def check_readme_table(registry_render: str, readme_text: str
                       ) -> List[Violation]:
    start = readme_text.find(TABLE_START)
    end = readme_text.find(TABLE_END)
    if start < 0 or end < 0 or end < start:
        return [Violation(
            "R5", README_PATH, 1,
            f"README is missing the {TABLE_START} / {TABLE_END} markers "
            "around the env-knob table",
            where="knob-table", key="R5:readme:markers")]
    current = readme_text[start + len(TABLE_START):end].strip()
    if current != registry_render.strip():
        lineno = readme_text[:start].count("\n") + 1
        return [Violation(
            "R5", README_PATH, lineno,
            "README knob table drifted from the tpu_config registry — "
            "regenerate with `python -m mythril_tpu.support.tpu_config` "
            "and paste between the markers",
            where="knob-table", key="R5:readme:drift")]
    return []


class EnvKnobRule(LintRule):
    code = "R5"
    name = "env-knobs"
    description = ("every MYTHRIL_TPU_* env read must be declared in "
                   "support/tpu_config.py; README knob table must match "
                   "the registry")

    def run(self, ctx: LintContext) -> List[Violation]:
        registry = load_registry()
        violations: List[Violation] = []
        for path in ctx.iter_py(*SCAN_DIRS):
            relpath = ctx.relpath(path)
            if relpath.startswith("tools/lint/") \
                    or relpath == "tools/check_excepts.py" \
                    or relpath.startswith("tests/data/lint/"):
                continue  # the linter and its fixtures mention knobs freely
            violations.extend(
                check_file(relpath, ctx.tree(path), registry))
        readme = os.path.join(ctx.repo_root, README_PATH)
        if os.path.exists(readme):
            violations.extend(
                check_readme_table(_render_table(), ctx.source(readme)))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        registry = load_registry()
        violations: List[Violation] = []
        for path in paths:
            violations.extend(
                check_file(ctx.relpath(path), ctx.tree(path), registry))
        return violations
