"""R3 — trace-safety: the device hot path must never silently sync.

Two classes of finding over ``mythril_tpu/parallel/``:

1. **Traced scope** (hard violations): inside any function that jax traces
   — jit/vmap/shard_map-wrapped, passed to a ``lax`` control-flow
   combinator, or (transitively) called from such a function — the
   following either crash at trace time or, worse, silently force a
   device→host transfer on every call:

   * ``.item()`` / ``.tolist()`` / ``.block_until_ready()``
   * ``np.*`` calls (host numpy materializes the traced value)
   * ``jax.device_get`` / ``np.asarray`` / ``np.array``
   * ``int()`` / ``float()`` / ``bool()`` on a non-constant value
   * Python ``if``/``while`` branching on a ``jnp``/``lax`` expression
     (the branch executes at trace time, not per-lane — semantic drift,
     or a ConcretizationTypeError at best)

   ``if x is None`` checks on static arguments are fine and not flagged.

2. **Host scope** (baseline-audited sync sites): every *explicit* sync
   primitive — ``jax.device_get(...)``, ``.item()``, ``.tolist()``,
   ``.block_until_ready()``, and ``bool()/int()/float()`` wrapped
   directly around a ``jnp``/``lax`` expression (the trace-boundary
   scalar fetch) — anywhere in ``parallel/`` must carry a baseline
   justification proving it is a deliberate bulk transfer (one drain per
   chunk) or a deliberate per-chunk control decision, not an accidental
   per-element tunnel read. The
   ~100 ms/transfer host tunnel is the single resource the frontier
   design spends most carefully; unaudited sync sites are how it leaks.

Keys: ``R3:<file>:<function>:<site>`` — line-number free so edits above a
site don't churn the baseline.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import LintContext, LintRule, Violation

SCAN_DIR = "mythril_tpu/parallel"

#: attribute/function names whose call wraps a function for tracing
TRACE_WRAPPERS = {"jit", "vmap", "pmap", "shard_map", "checkpoint", "remat"}

#: jax.lax combinators whose function arguments are traced
LAX_CONTROL = {"fori_loop", "scan", "while_loop", "cond", "switch", "map",
               "associative_scan", "custom_root"}

#: method calls that force a device->host sync
SYNC_METHODS = {"item", "tolist", "block_until_ready"}

#: names numpy is commonly imported as
NUMPY_ALIASES = {"np", "numpy", "onp"}

#: names jax.numpy / jax.lax are commonly bound to
DEVICE_NS = {"jnp", "lax", "jax"}


def _func_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _ns_of(func: ast.AST) -> Optional[str]:
    """Leading namespace of a call target: `np.asarray` -> 'np',
    `jax.lax.scan` -> 'jax', bare name -> None."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


class _ModuleIndex:
    """Per-module symbol tables the closure pass needs."""

    def __init__(self, relpath: str, tree: ast.AST):
        self.relpath = relpath
        self.tree = tree
        #: bare function name -> def node (top-level and class methods)
        self.functions: Dict[str, ast.AST] = {}
        #: local alias -> sibling module name ("A" -> "arena")
        self.module_aliases: Dict[str, str] = {}
        #: function names traced in this module (roots + closure)
        self.traced: Set[str] = set()
        #: lambda/def nodes directly handed to a tracer from host scope
        self.traced_nodes: List[ast.AST] = []
        self._index()

    def _index(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)
            elif isinstance(node, ast.ImportFrom):
                # `from . import arena as A` / `from . import words`
                if node.module in (None, "") or node.level:
                    for alias in node.names:
                        self.module_aliases[alias.asname or alias.name] = \
                            alias.name
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    tail = alias.name.rsplit(".", 1)[-1]
                    self.module_aliases.setdefault(
                        alias.asname or tail, tail)

    # -- root detection ----------------------------------------------------------

    def _mark(self, node: ast.AST) -> None:
        """Mark a function reference/literal as traced."""
        if isinstance(node, ast.Name) and node.id in self.functions:
            self.traced.add(node.id)
        elif isinstance(node, (ast.Lambda, ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            self.traced_nodes.append(node)
        elif isinstance(node, ast.Call):
            # jax.jit(jax.vmap(fn)) — unwrap nested wrapper calls
            name = _func_name(node.func)
            if name in TRACE_WRAPPERS or name == "partial":
                for arg in node.args:
                    self._mark(arg)

    def find_roots(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if self._is_trace_wrapper(deco):
                        self.traced.add(node.name)
            elif isinstance(node, ast.Call):
                name = _func_name(node.func)
                if name in TRACE_WRAPPERS:
                    for arg in node.args:
                        self._mark(arg)
                elif name == "partial":
                    # partial(jax.jit, ...) used as a decorator is caught
                    # above; partial(fn) itself traces nothing
                    pass
                elif name in LAX_CONTROL and _ns_of(node.func) in DEVICE_NS:
                    for arg in node.args:
                        self._mark(arg)

    def _is_trace_wrapper(self, deco: ast.AST) -> bool:
        name = _func_name(deco)
        if name in TRACE_WRAPPERS:
            return True
        if isinstance(deco, ast.Call):
            inner = _func_name(deco.func)
            if inner in TRACE_WRAPPERS:
                return True
            if inner == "partial" and deco.args \
                    and _func_name(deco.args[0]) in TRACE_WRAPPERS:
                return True
        return False


def _transitive_closure(indexes: Dict[str, _ModuleIndex]) -> None:
    """Functions called (by bare name or module-alias attribute) from a
    traced function are traced too — `step` via `lockstep.step`,
    `alloc_rows` via `A.alloc_rows`."""
    by_module = {idx.relpath.rsplit("/", 1)[-1][:-3]: idx
                 for idx in indexes.values()}
    work: List[Tuple[_ModuleIndex, ast.AST]] = []
    seen: Set[Tuple[str, int]] = set()

    def push(idx: _ModuleIndex, fn: ast.AST) -> None:
        key = (idx.relpath, id(fn))
        if key not in seen:
            seen.add(key)
            work.append((idx, fn))

    for idx in indexes.values():
        for name in idx.traced:
            push(idx, idx.functions[name])
        for node in idx.traced_nodes:
            push(idx, node)

    while work:
        idx, fn = work.pop()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in idx.functions:
                idx.traced.add(func.id)
                push(idx, idx.functions[func.id])
            elif isinstance(func, ast.Attribute) \
                    and isinstance(func.value, ast.Name):
                mod = idx.module_aliases.get(func.value.id)
                target = by_module.get(mod) if mod else None
                if target and func.attr in target.functions:
                    target.traced.add(func.attr)
                    push(target, target.functions[func.attr])


def _test_touches_device(test: ast.AST) -> bool:
    """Does a branch condition contain a jnp./lax./jax. call? (`x is None`
    and plain-python comparisons are static and fine.)"""
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and _ns_of(node.func) in DEVICE_NS:
            return True
    return False


def _scan_traced_body(relpath: str, fn: ast.AST, fn_name: str
                      ) -> List[Violation]:
    violations = []

    def add(node: ast.AST, site: str, detail: str) -> None:
        violations.append(Violation(
            "R3", relpath, node.lineno, detail, where=fn_name,
            key=f"R3:{relpath}:{fn_name}:{site}"))

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _func_name(node.func)
            ns = _ns_of(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and name in SYNC_METHODS and ns not in NUMPY_ALIASES:
                add(node, name,
                    f".{name}() inside traced function {fn_name}() forces "
                    "a device->host sync on every trace evaluation — keep "
                    "the value on device (jnp) or hoist to the host driver")
            elif ns in NUMPY_ALIASES:
                add(node, f"np.{name}",
                    f"host numpy call np.{name}() inside traced function "
                    f"{fn_name}() materializes the traced value — use "
                    "jnp, or hoist the conversion to the host driver")
            elif name == "device_get":
                add(node, "device_get",
                    f"jax.device_get inside traced function {fn_name}() — "
                    "a traced value cannot be fetched mid-trace")
            elif isinstance(node.func, ast.Name) \
                    and name in ("int", "float", "bool") and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                add(node, name,
                    f"{name}() on a traced value in {fn_name}() raises "
                    "ConcretizationTypeError under jit (or silently syncs "
                    "outside it) — use astype()/jnp casts instead")
        elif isinstance(node, (ast.If, ast.While)) \
                and _test_touches_device(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            add(node, f"branch-{kind}",
                f"Python `{kind}` on a jnp/lax expression in {fn_name}() "
                "branches at trace time, not per lane — use jnp.where/"
                "lax.cond so every lane keeps its own path")
    return violations


def _scan_host_syncs(relpath: str, tree: ast.AST,
                     traced_fns: Set[str]) -> List[Violation]:
    from .silent_excepts import enclosing_function

    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        site = None
        name = _func_name(node.func)
        if isinstance(node.func, ast.Attribute) and name in SYNC_METHODS \
                and _ns_of(node.func) not in NUMPY_ALIASES:
            site = name
        elif name == "device_get":
            site = "device_get"
        elif isinstance(node.func, ast.Name) \
                and name in ("int", "float", "bool") and node.args \
                and _test_touches_device(node.args[0]):
            site = f"{name}-of-device"
        if site is None:
            continue
        fn = enclosing_function(tree, node) or "<module>"
        if fn in traced_fns:
            continue  # already reported as a traced-scope violation
        violations.append(Violation(
            "R3", relpath, node.lineno,
            f"explicit host sync `{site}` in {fn}() — every sync site in "
            "parallel/ must be a justified bulk transfer "
            "(tools/lint/baseline.json), never a per-element tunnel read",
            where=fn, key=f"R3:{relpath}:{fn}:{site}"))
    return violations


def analyze_modules(modules: Iterable[Tuple[str, ast.AST]]
                    ) -> List[Violation]:
    """Full R3 over a set of (relpath, tree) modules: root detection,
    cross-module traced closure, traced-scope scan, host sync-site scan."""
    indexes = {relpath: _ModuleIndex(relpath, tree)
               for relpath, tree in modules}
    for idx in indexes.values():
        idx.find_roots()
    _transitive_closure(indexes)

    violations: List[Violation] = []
    for idx in indexes.values():
        seen_nodes = set()
        for name in sorted(idx.traced):
            fn = idx.functions[name]
            seen_nodes.add(id(fn))
            violations.extend(_scan_traced_body(idx.relpath, fn, name))
        for node in idx.traced_nodes:
            if id(node) not in seen_nodes:
                label = getattr(node, "name", "<lambda>")
                violations.extend(
                    _scan_traced_body(idx.relpath, node, label))
        violations.extend(
            _scan_host_syncs(idx.relpath, idx.tree, idx.traced))
    return violations


class TraceSafetyRule(LintRule):
    code = "R3"
    name = "trace-safety"
    description = ("no implicit host<->device syncs or trace-time branching "
                   "in jit/vmap hot paths; explicit sync sites in parallel/ "
                   "need a baseline justification")

    def run(self, ctx: LintContext) -> List[Violation]:
        modules = [(ctx.relpath(path), ctx.tree(path))
                   for path in ctx.iter_py(SCAN_DIR)]
        return analyze_modules(modules)

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        # the given files form one module group, so cross-file traced
        # closure still works when a driver and its jitted helpers are
        # passed together
        return analyze_modules(
            [(ctx.relpath(path), ctx.tree(path)) for path in paths])
