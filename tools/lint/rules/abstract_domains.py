"""R9 — abstract-domain ownership: value-range / stack-shape static
reasoning belongs to ``mythril_tpu/staticanalysis/``.

``staticanalysis/cfa.py`` (the baselined producer) and
``staticanalysis/absint.py`` already simulate abstract stacks, fold
PUSH immediates, and run stride-interval arithmetic once per contract;
consumers read the memoized verdicts through
``smt/solver/cfa_screen.py`` (``jumpi_verdict``, ``loop_bound_at``,
``merge_mem_windows``) exactly like R7's jump tables. A module that
re-folds PUSH constants or re-simulates stack heights forks that
domain: its copy silently diverges the moment the shared pass learns a
refinement (new transfer function, tighter widening), and the absint
A/B counters stop describing the run.

Flagged outside ``mythril_tpu/staticanalysis/``:

* a PUSH-immediate fold — ``int(X, 16)`` where ``X`` mentions an
  ``argument`` name/attribute/key (the disassembly instruction-dict
  idiom; generic hex parsing without ``argument`` is fine);
* stack-height simulation — arithmetic combining ``pushes`` and
  ``pops`` operands (re-deriving stack effects instead of reading the
  CFA's ``entry_height`` / ``block_key`` tables);
* an ad-hoc interval domain — a class or function named like an
  abstract domain (``Interval``, ``StrideInterval``, ``ValueRange``,
  ``make_interval``, ``join_iv``, ``widen_iv``, ``interval_binary``).

The legitimate non-static owners carry justified baseline entries: the
disassembler (produces the instruction stream the folds read), the
host PUSH handler and the device lockstep interpreter (they *execute*
immediates and stack effects rather than statically simulating them).
"""

from __future__ import annotations

import ast
from typing import List

from .. import LintContext, LintRule, Violation

SCAN_DIRS = ("mythril_tpu", "tools", "tests", "bench.py")
ALLOWED_PREFIX = "mythril_tpu/staticanalysis/"

IMMEDIATE_NAME = "argument"
DOMAIN_NAMES = ("Interval", "StrideInterval", "ValueRange",
                "make_interval", "join_iv", "widen_iv",
                "interval_binary")
STACK_EFFECT_NAMES = ("pushes", "pops")


def _mentions_name(node: ast.AST, name: str) -> bool:
    """`name` appears under `node` as a Name, an Attribute, or a
    constant subscript/string key."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == name:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == name:
            return True
        if isinstance(sub, ast.Constant) and sub.value == name:
            return True
    return False


def _is_base16_int(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "int"
            and len(node.args) == 2
            and isinstance(node.args[1], ast.Constant)
            and node.args[1].value == 16)


def check_file(relpath: str, tree: ast.AST) -> List[Violation]:
    violations: List[Violation] = []

    seen_tags: dict = {}

    def flag(lineno: int, how: str, tag: str) -> None:
        # stable, line-free keys: same-kind repeats get an ordinal suffix
        # (walk order is deterministic for a given file)
        ordinal = seen_tags.get(tag, 0)
        seen_tags[tag] = ordinal + 1
        if ordinal:
            tag = f"{tag}#{ordinal}"
        violations.append(Violation(
            "R9", relpath, lineno,
            f"{how} re-implements abstract-domain reasoning — consume "
            "the shared value-range tables instead "
            "(staticanalysis.get_absint / smt/solver/cfa_screen.py: "
            "jumpi_verdict, loop_bound_at, merge_mem_windows)",
            where=tag, key=f"R9:{relpath}:{tag}"))

    for node in ast.walk(tree):
        if _is_base16_int(node) \
                and _mentions_name(node.args[0], IMMEDIATE_NAME):
            flag(node.lineno,
                 "`int(..., 16)` over an instruction `argument` "
                 "(PUSH-immediate fold)", "push-fold")
        elif isinstance(node, ast.BinOp):
            # pushes/pops combined arithmetically = stack-effect
            # simulation; skip nested BinOps so one expression tree
            # yields one violation (the outermost match wins)
            if _mentions_name(node.left, STACK_EFFECT_NAMES[0]) \
                    and _mentions_name(node, STACK_EFFECT_NAMES[1]) \
                    or _mentions_name(node.left, STACK_EFFECT_NAMES[1]) \
                    and _mentions_name(node, STACK_EFFECT_NAMES[0]):
                flag(node.lineno,
                     "arithmetic over `pushes`/`pops` (stack-height "
                     "simulation)", "stack-sim")
        elif isinstance(node, (ast.ClassDef, ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            if node.name in DOMAIN_NAMES:
                kind = "class" if isinstance(node, ast.ClassDef) \
                    else "function"
                flag(node.lineno,
                     f"{kind} `{node.name}` (ad-hoc interval domain)",
                     f"domain:{node.name}")
    return violations


class AbstractDomainsRule(LintRule):
    code = "R9"
    name = "abstract-domains"
    description = ("value-range / stack-shape static reasoning (PUSH "
                   "folds, stack-height simulation, interval "
                   "arithmetic) belongs to staticanalysis/ — consumers "
                   "read the absint verdicts via "
                   "smt/solver/cfa_screen.py")

    def run(self, ctx: LintContext) -> List[Violation]:
        violations: List[Violation] = []
        for path in ctx.iter_py(*SCAN_DIRS):
            relpath = ctx.relpath(path)
            if relpath.startswith(ALLOWED_PREFIX) \
                    or relpath.startswith("tools/lint/") \
                    or relpath == "tools/check_excepts.py" \
                    or relpath.startswith("tests/data/lint/"):
                continue
            violations.extend(check_file(relpath, ctx.tree(path)))
        return violations

    def check_paths(self, ctx: LintContext, paths) -> List[Violation]:
        violations: List[Violation] = []
        for path in paths:
            violations.extend(
                check_file(ctx.relpath(path), ctx.tree(path)))
        return violations
