"""Value-range/memory-region absint smoke for the pre-merge gate
(tools/check.sh).

Stdlib + in-repo frontends only (no jax import, no symbolic
execution), so it runs in a couple of seconds:

1. build the absint tables for both vendored headline contracts
   (killbilly, bectoken) and require a converged fixpoint with
   non-empty entry intervals and at least one bounded block write
   region;
2. on a hand-assembled diamond whose arms both MSTORE offset 0,
   require the join region [0, 32) to be proven and exactly one
   32-byte merge window derived — the static fact behind the widened
   memory-plane merge (parallel/symstep.py merge_pass);
3. on a hand-assembled counting loop, require the proven
   header-arrival bound (core/strategy/bounded_loops.py consumer);
4. on a constant-condition branch, require the JUMPI verdict
   (smt/solver/cfa_screen.py jumpi_verdict consumer);
5. require the MYTHRIL_TPU_ABSINT=0 gate to disable the memoized
   accessor (the --no-absint A/B contract).

Prints ``ABSINT_SMOKE=ok`` on success; any failure exits non-zero
with a diagnostic.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: diamond on a calldata word: both arms MSTORE a different constant
#: at offset 0 and push the same stack value before the join
DIAMOND = """
PUSH1 0x00
CALLDATALOAD
PUSH @odd
JUMPI
PUSH1 0x07
PUSH1 0x00
MSTORE
PUSH1 0x05
PUSH @join
JUMP
odd:
JUMPDEST
PUSH1 0x09
PUSH1 0x00
MSTORE
PUSH1 0x05
join:
JUMPDEST
POP
STOP
"""

#: i = 0; while i != 5: i += 1 — five iterations, six header arrivals
LOOP = """
PUSH1 0x00
head:
JUMPDEST
DUP1
PUSH1 0x05
EQ
PUSH @exit
JUMPI
PUSH1 0x01
ADD
PUSH @head
JUMP
exit:
JUMPDEST
POP
STOP
"""

#: JUMPI on a provably-true condition (PUSH1 1)
CONST_BRANCH = """
PUSH1 0x01
PUSH @live
JUMPI
PUSH1 0x00
PUSH1 0x00
REVERT
live:
JUMPDEST
STOP
"""


def _build(asm: str):
    from mythril_tpu.frontends.asm import assemble
    from mythril_tpu.frontends.disassembler import Disassembly
    from mythril_tpu.staticanalysis import build_absint, build_cfa

    disassembly = Disassembly(assemble(asm).hex())
    cfa = build_cfa(disassembly)
    if cfa is None:
        return None, None
    return build_absint(disassembly, cfa), cfa


def main() -> int:
    from mythril_tpu.frontends.asm import assemble, dispatcher
    from mythril_tpu.frontends.disassembler import Disassembly
    from mythril_tpu.staticanalysis import build_absint, get_absint
    from tools.measure_headline import BECTOKEN, KILLBILLY

    # 1) vendored corpus: converged tables with bounded write regions
    for name, spec in (("killbilly", KILLBILLY), ("bectoken", BECTOKEN)):
        disassembly = Disassembly(assemble(dispatcher(spec)).hex())
        result = build_absint(disassembly)
        if result is None:
            print(f"absint_smoke: fixpoint bailed on {name}",
                  file=sys.stderr)
            return 1
        if not result.entry_intervals:
            print(f"absint_smoke: no entry intervals for {name}",
                  file=sys.stderr)
            return 1
        bounded = [regions for regions in result.block_writes.values()
                   if regions]
        if not bounded:
            print(f"absint_smoke: no bounded write region on {name}",
                  file=sys.stderr)
            return 1

    # 2) diamond: proven join region + exactly one 32-byte window
    result, cfa = _build(DIAMOND)
    if result is None:
        print("absint_smoke: diamond fixpoint bailed", file=sys.stderr)
        return 1
    if not cfa.branch_merge_pc:
        print("absint_smoke: diamond has no recovered join",
              file=sys.stderr)
        return 1
    join_pc = next(iter(cfa.branch_merge_pc.values()))
    regions = result.join_regions.get(join_pc)
    if regions != ((0, 32),):
        print(f"absint_smoke: diamond join region {regions!r}, "
              "want ((0, 32),)", file=sys.stderr)
        return 1
    if result.word_windows(join_pc) != (0,):
        print(f"absint_smoke: diamond windows "
              f"{result.word_windows(join_pc)!r}, want (0,)",
              file=sys.stderr)
        return 1

    # 3) counting loop: proven header-arrival bound (5 iters -> 6)
    result, _ = _build(LOOP)
    if result is None or not result.loop_bounds:
        print("absint_smoke: loop bound not proven", file=sys.stderr)
        return 1
    bound = next(iter(result.loop_bounds.values()))
    if bound != 6:
        print(f"absint_smoke: loop bound {bound}, want 6",
              file=sys.stderr)
        return 1

    # 4) constant branch: static always-taken verdict
    result, _ = _build(CONST_BRANCH)
    if result is None or True not in result.const_jumpis.values():
        print("absint_smoke: constant JUMPI not proven", file=sys.stderr)
        return 1

    # 5) the A/B gate: MYTHRIL_TPU_ABSINT=0 disables the accessor
    disassembly = Disassembly(assemble(CONST_BRANCH).hex())
    old = os.environ.get("MYTHRIL_TPU_ABSINT")
    os.environ["MYTHRIL_TPU_ABSINT"] = "0"
    try:
        if get_absint(disassembly) is not None:
            print("absint_smoke: MYTHRIL_TPU_ABSINT=0 did not gate "
                  "get_absint", file=sys.stderr)
            return 1
    finally:
        if old is None:
            os.environ.pop("MYTHRIL_TPU_ABSINT", None)
        else:
            os.environ["MYTHRIL_TPU_ABSINT"] = old

    print("ABSINT_SMOKE=ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
