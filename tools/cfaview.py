#!/usr/bin/env python
"""Static-CFA report CLI for mythril-tpu.

    python -m tools.cfaview CONTRACT

CONTRACT is one of:

* a path to a file holding hex runtime bytecode (``*.sol.o``, ``.hex``,
  with or without a ``0x`` prefix / trailing whitespace);
* a raw hex string (``0x6080...`` or bare);
* a vendored contract name: ``killbilly`` or ``bectoken`` (the
  hand-assembled headline contracts from tools/measure_headline.py).

Prints the cfa verdict (mythril_tpu/staticanalysis/): summary counters,
the basic-block table (pc range, terminator, successors, entry stack
height, post-dominator merge pc), resolved/unresolved jump sites, branch
merge points, and statically-dead code regions. ``--taint`` appends the
source->sink taint summary: recovered public functions (selectors),
natural loops, per-sink operand taint verdicts, and the detection
modules the module screen would skip wholesale. ``--absint`` appends
the value-range/memory-region verdict (staticanalysis/absint.py):
per-block entry stride-intervals, per-block write regions, join-point
memory windows (what the widened merge phase ships to the device),
statically proven loop trip bounds, and provably-constant JUMPIs.
``--json`` dumps the raw tables instead (with ``taint`` / ``absint``
keys under the matching flags; the ``absint`` document round-trips
through ``AbsintResult.from_json``).

Host-only (the cfa pass is stdlib + in-repo frontends; no jax import).
Exit codes: 0 on success, 2 when the input is missing/undecodable or the
pass bails (block budget).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_VENDORED = ("killbilly", "bectoken")


def _vendored_bytecode(name: str) -> str:
    from mythril_tpu.frontends.asm import assemble, dispatcher
    from tools.measure_headline import BECTOKEN, KILLBILLY

    functions = KILLBILLY if name == "killbilly" else BECTOKEN
    return assemble(dispatcher(functions)).hex()


def load_bytecode(spec: str) -> str:
    """Resolve CONTRACT to a hex bytecode string. Raises ValueError."""
    if spec.lower() in _VENDORED:
        return _vendored_bytecode(spec.lower())
    if os.path.exists(spec):
        with open(spec, "r", encoding="utf-8") as handle:
            text = handle.read().strip()
    else:
        text = spec.strip()
    if text.startswith(("0x", "0X")):
        text = text[2:]
    text = "".join(text.split())
    if not text:
        raise ValueError("empty bytecode")
    int(text, 16)  # raises ValueError on non-hex
    if len(text) % 2:
        raise ValueError("odd-length hex string")
    return text


def _succ_str(block, result) -> str:
    parts = []
    for succ in sorted(block.successors):
        parts.append("EXIT" if succ == result.exit_id
                     else f"B{succ}@{result.blocks[succ].start_pc:#x}")
    return ",".join(parts) if parts else "-"


def _dead_regions(result) -> List[tuple]:
    regions, start = [], None
    for pc, dead in enumerate(result.dead_mask):
        if dead and start is None:
            start = pc
        elif not dead and start is not None:
            regions.append((start, pc))
            start = None
    if start is not None:
        regions.append((start, len(result.dead_mask)))
    return regions


def report(result, instructions) -> str:
    lines: List[str] = []
    n_reach = len(result.reachable)
    lines.append("== summary ==")
    lines.append(f"  code: {result.code_length} bytes, "
                 f"{len(instructions)} instructions")
    lines.append(f"  blocks: {len(result.blocks)} "
                 f"({n_reach} reachable), edges: {result.n_edges}")
    lines.append(f"  jump sites: {result.n_jump_sites} "
                 f"({len(result.jump_targets)} resolved, "
                 f"{len(result.unresolved_jumps)} unresolved"
                 + (", fully resolved)" if result.fully_resolved else ")"))
    lines.append(f"  valid targets (reachable JUMPDESTs): "
                 f"{len(result.valid_targets)}")
    lines.append(f"  merge points: {len(result.merge_points)}, "
                 f"dead code: {result.dead_bytes} bytes")

    lines.append("")
    lines.append("== blocks ==")
    lines.append(f"  {'id':>4} {'pc range':>15} {'term':<10} {'h':>4} "
                 f"{'merge':>7}  successors")
    for block in result.blocks:
        dead = block.block_id not in result.reachable
        height = "?" if block.entry_height is None else block.entry_height
        merge = result.block_merge_pc[block.block_id]
        lines.append(
            f"  {block.block_id:>4} "
            f"{block.start_pc:#7x}..{block.end_pc:#6x} "
            f"{(block.terminator or 'fall'):<10} {height:>4} "
            f"{(f'{merge:#x}' if merge >= 0 else '-'):>7}  "
            + ("DEAD" if dead else _succ_str(block, result)))

    lines.append("")
    lines.append("== jump sites ==")
    if not result.jump_targets and not result.unresolved_jumps:
        lines.append("  (none reachable)")
    for site in sorted(result.jump_targets):
        targets = result.jump_targets[site]
        dest = ", ".join(f"{t:#x}" for t in targets) if targets \
            else "(provably throws)"
        lines.append(f"  {site:#6x} -> {dest}")
    for site in sorted(result.unresolved_jumps):
        lines.append(f"  {site:#6x} -> ?  (unresolved: conservative "
                     f"fan-out to every JUMPDEST)")

    lines.append("")
    lines.append("== merge points (branch site -> postdom pc) ==")
    if result.branch_merge_pc:
        for site in sorted(result.branch_merge_pc):
            lines.append(f"  {site:#6x} -> {result.branch_merge_pc[site]:#x}")
    else:
        lines.append("  (no branch reconverges before exit)")

    regions = _dead_regions(result)
    lines.append("")
    lines.append("== statically dead code ==")
    if regions:
        for start, end in regions:
            lines.append(f"  {start:#6x}..{end:#x}  ({end - start} bytes)")
    else:
        lines.append("  (none)")
    return "\n".join(lines)


def _screened_module_names(disassembly) -> List[str]:
    """Detection modules the module screen would skip wholesale for this
    contract (hook opcodes unreachable)."""
    from mythril_tpu.analysis.module import ModuleLoader
    from mythril_tpu.analysis.module.base import EntryPoint
    from mythril_tpu.analysis.module_screen import screen_modules

    modules = ModuleLoader().get_detection_modules(EntryPoint.CALLBACK)
    _, skipped = screen_modules(modules, disassembly)
    return sorted(type(m).__name__ for m in skipped)


def _taints_str(taints) -> str:
    return ",".join(sorted(taints)) if taints else "-"


def taint_report(summary, disassembly) -> str:
    lines: List[str] = []
    lines.append("")
    lines.append("== taint: functions ==")
    if summary.functions:
        for fn in summary.functions:
            lines.append(f"  {fn.entry_pc:#6x} {fn.selector or '(fallback)':<12} "
                         f"{fn.name}  ({len(fn.blocks)} block(s))")
    else:
        lines.append("  (no dispatcher recovered — single partition)")

    lines.append("")
    lines.append("== taint: natural loops ==")
    if summary.loops:
        for loop in summary.loops:
            backs = ", ".join(f"{pc:#x}" for pc in loop.back_edge_pcs)
            lines.append(f"  header {loop.header_pc:#6x} depth {loop.depth} "
                         f"({len(loop.blocks)} block(s), back edges: {backs})")
    else:
        lines.append("  (none)")

    lines.append("")
    lines.append("== taint: sink sites (operand 0 = top of stack) ==")
    converged = "converged" if summary.converged else "NOT converged (saturated)"
    lines.append(f"  {len(summary.sink_sites)} site(s), "
                 f"{summary.rounds} storage round(s), {converged}")
    for pc in sorted(summary.sink_sites):
        site = summary.sink_sites[pc]
        operands = "  ".join(
            f"[{i}]={_taints_str(t)}"
            for i, t in enumerate(site.operand_taint))
        lines.append(f"  {pc:#6x} {site.op:<14} {operands}")

    lines.append("")
    lines.append("== taint: module screen ==")
    skipped = _screened_module_names(disassembly)
    if skipped:
        lines.append(f"  {len(skipped)} module(s) skipped wholesale "
                     "(hook opcodes unreachable):")
        for name in skipped:
            lines.append(f"    {name}")
    else:
        lines.append("  (no whole-module skips)")
    return "\n".join(lines)


def _iv_str(iv) -> str:
    """Compact stride-interval rendering: constants as hex, TOP as T,
    everything else as [lo..hi /stride]."""
    from mythril_tpu.staticanalysis.absint import TOP

    lo, hi, stride = iv
    if iv == TOP:
        return "T"
    if stride == 0:
        return f"{lo:#x}"
    return f"[{lo:#x}..{hi:#x} /{stride}]"


def absint_report(absint, cfa) -> str:
    lines: List[str] = []
    lines.append("")
    lines.append("== absint: summary ==")
    lines.append(f"  fixpoint: {absint.iterations} iteration(s), "
                 f"{absint.widenings} widening(s), "
                 f"{len(absint.entry_intervals)} block(s) tracked")
    lines.append(f"  proven: {absint.regions_proven} join region(s), "
                 f"{len(absint.loop_bounds)} loop bound(s), "
                 f"{len(absint.const_jumpis)} constant JUMPI(s)")

    lines.append("")
    lines.append("== absint: block entry ranges (top -> deep) ==")
    for block_id in sorted(absint.entry_intervals):
        height, cells = absint.entry_intervals[block_id]
        start_pc = cfa.blocks[block_id].start_pc
        if height is None:
            lines.append(f"  B{block_id:<3} {start_pc:#6x}  h=?  (unknown "
                         "entry — unresolved-jump fan-in)")
            continue
        stack = "  ".join(_iv_str(iv) for iv in cells) or "-"
        lines.append(f"  B{block_id:<3} {start_pc:#6x}  h={height:<3} {stack}")

    lines.append("")
    lines.append("== absint: block write regions ==")
    any_write = False
    for block_id in sorted(absint.block_writes):
        regions = absint.block_writes[block_id]
        if regions == ():
            continue
        any_write = True
        start_pc = cfa.blocks[block_id].start_pc
        body = "TOP (unbounded/symbolic offset)" if regions is None else \
            " ".join(f"[{a:#x},{b:#x})" for a, b in regions)
        lines.append(f"  B{block_id:<3} {start_pc:#6x}  {body}")
    if not any_write:
        lines.append("  (no block writes memory)")

    lines.append("")
    lines.append("== absint: join-point memory windows ==")
    if absint.join_regions:
        for pc in sorted(absint.join_regions):
            regions = absint.join_regions[pc]
            windows = absint.word_windows(pc)
            body = " ".join(f"[{a:#x},{b:#x})" for a, b in regions) or \
                "(no writes on either arm)"
            wtxt = ("windows " + " ".join(f"{w:#x}" for w in windows)
                    if windows else
                    "no windows needed" if windows == () else
                    "over the window cap — widened merge skipped")
            lines.append(f"  join {pc:#6x}: {body}  -> {wtxt}")
    else:
        lines.append("  (no diamond proves a bounded write region)")

    lines.append("")
    lines.append("== absint: proven loop bounds (header arrivals) ==")
    if absint.loop_bounds:
        for pc in sorted(absint.loop_bounds):
            lines.append(f"  header {pc:#6x} -> {absint.loop_bounds[pc]}")
    else:
        lines.append("  (no loop trip count proven)")

    lines.append("")
    lines.append("== absint: constant JUMPIs ==")
    if absint.const_jumpis:
        for pc in sorted(absint.const_jumpis):
            verdict = ("always taken" if absint.const_jumpis[pc]
                       else "never taken")
            lines.append(f"  {pc:#6x} -> {verdict}")
    else:
        lines.append("  (no provably-constant branch)")
    return "\n".join(lines)


def as_json(result) -> dict:
    """The dense tables, JSON-serializable (dict keys become strings)."""
    return {
        "code_length": result.code_length,
        "blocks": [
            {"id": b.block_id, "start_pc": b.start_pc, "end_pc": b.end_pc,
             "terminator": b.terminator, "entry_height": b.entry_height,
             "successors": sorted(b.successors),
             "reachable": b.block_id in result.reachable}
            for b in result.blocks],
        "exit_id": result.exit_id,
        "n_edges": result.n_edges,
        "pc_to_block": list(result.pc_to_block),
        "block_merge_pc": list(result.block_merge_pc),
        "branch_merge_pc": {str(pc): merge for pc, merge
                            in sorted(result.branch_merge_pc.items())},
        "valid_targets": sorted(result.valid_targets),
        "jump_targets": {str(pc): list(targets) for pc, targets
                         in sorted(result.jump_targets.items())},
        "unresolved_jumps": sorted(result.unresolved_jumps),
        "dead_mask": [int(dead) for dead in result.dead_mask],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.cfaview",
        description="static control-flow-analysis report for EVM "
                    "runtime bytecode")
    parser.add_argument("contract",
                        help="hex bytecode file, raw hex string, or a "
                             f"vendored name ({'/'.join(_VENDORED)})")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw cfa tables as JSON")
    parser.add_argument("--taint", action="store_true",
                        help="append the source->sink taint summary "
                             "(functions, loops, sink verdicts, module "
                             "screen)")
    parser.add_argument("--absint", action="store_true",
                        help="append the value-range/memory-region "
                             "verdict (per-block entry intervals, write "
                             "regions, join windows, proven loop bounds, "
                             "constant JUMPIs)")
    args = parser.parse_args(argv)
    try:
        bytecode = load_bytecode(args.contract)
    except (OSError, ValueError) as error:
        print(f"cfaview: cannot load {args.contract!r}: {error}",
              file=sys.stderr)
        return 2

    from mythril_tpu.frontends.disassembler import Disassembly
    from mythril_tpu.staticanalysis import build_cfa

    disassembly = Disassembly(bytecode)
    result = build_cfa(disassembly)
    if result is None:
        print("cfaview: cfa pass bailed (empty code or over the "
              "MYTHRIL_TPU_CFA_MAX_BLOCKS budget)", file=sys.stderr)
        return 2
    summary = None
    if args.taint:
        from mythril_tpu.staticanalysis import get_summary

        summary = get_summary(disassembly)
        if summary is None:
            print("cfaview: taint summary unavailable (pass disabled "
                  "via MYTHRIL_TPU_TAINT=0, or the fixpoint bailed)",
                  file=sys.stderr)
            return 2
    absint = None
    if args.absint:
        from mythril_tpu.staticanalysis import build_absint

        absint = build_absint(disassembly, result)
        if absint is None:
            print("cfaview: absint verdict unavailable (the fixpoint "
                  "bailed — iteration budget)", file=sys.stderr)
            return 2
    if args.json:
        import json
        doc = as_json(result)
        if summary is not None:
            doc["taint"] = summary.to_json()
            doc["taint"]["screened_modules"] = \
                _screened_module_names(disassembly)
        if absint is not None:
            doc["absint"] = absint.to_json()
        print(json.dumps(doc, indent=2))
    else:
        text = report(result, disassembly.instruction_list)
        if summary is not None:
            text += "\n" + taint_report(summary, disassembly)
        if absint is not None:
            text += "\n" + absint_report(absint, result)
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
