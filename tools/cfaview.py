#!/usr/bin/env python
"""Static-CFA report CLI for mythril-tpu.

    python -m tools.cfaview CONTRACT

CONTRACT is one of:

* a path to a file holding hex runtime bytecode (``*.sol.o``, ``.hex``,
  with or without a ``0x`` prefix / trailing whitespace);
* a raw hex string (``0x6080...`` or bare);
* a vendored contract name: ``killbilly`` or ``bectoken`` (the
  hand-assembled headline contracts from tools/measure_headline.py).

Prints the cfa verdict (mythril_tpu/staticanalysis/): summary counters,
the basic-block table (pc range, terminator, successors, entry stack
height, post-dominator merge pc), resolved/unresolved jump sites, branch
merge points, and statically-dead code regions. ``--taint`` appends the
source->sink taint summary: recovered public functions (selectors),
natural loops, per-sink operand taint verdicts, and the detection
modules the module screen would skip wholesale. ``--json`` dumps the
raw tables instead (with a ``taint`` key under ``--taint``).

Host-only (the cfa pass is stdlib + in-repo frontends; no jax import).
Exit codes: 0 on success, 2 when the input is missing/undecodable or the
pass bails (block budget).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_VENDORED = ("killbilly", "bectoken")


def _vendored_bytecode(name: str) -> str:
    from mythril_tpu.frontends.asm import assemble, dispatcher
    from tools.measure_headline import BECTOKEN, KILLBILLY

    functions = KILLBILLY if name == "killbilly" else BECTOKEN
    return assemble(dispatcher(functions)).hex()


def load_bytecode(spec: str) -> str:
    """Resolve CONTRACT to a hex bytecode string. Raises ValueError."""
    if spec.lower() in _VENDORED:
        return _vendored_bytecode(spec.lower())
    if os.path.exists(spec):
        with open(spec, "r", encoding="utf-8") as handle:
            text = handle.read().strip()
    else:
        text = spec.strip()
    if text.startswith(("0x", "0X")):
        text = text[2:]
    text = "".join(text.split())
    if not text:
        raise ValueError("empty bytecode")
    int(text, 16)  # raises ValueError on non-hex
    if len(text) % 2:
        raise ValueError("odd-length hex string")
    return text


def _succ_str(block, result) -> str:
    parts = []
    for succ in sorted(block.successors):
        parts.append("EXIT" if succ == result.exit_id
                     else f"B{succ}@{result.blocks[succ].start_pc:#x}")
    return ",".join(parts) if parts else "-"


def _dead_regions(result) -> List[tuple]:
    regions, start = [], None
    for pc, dead in enumerate(result.dead_mask):
        if dead and start is None:
            start = pc
        elif not dead and start is not None:
            regions.append((start, pc))
            start = None
    if start is not None:
        regions.append((start, len(result.dead_mask)))
    return regions


def report(result, instructions) -> str:
    lines: List[str] = []
    n_reach = len(result.reachable)
    lines.append("== summary ==")
    lines.append(f"  code: {result.code_length} bytes, "
                 f"{len(instructions)} instructions")
    lines.append(f"  blocks: {len(result.blocks)} "
                 f"({n_reach} reachable), edges: {result.n_edges}")
    lines.append(f"  jump sites: {result.n_jump_sites} "
                 f"({len(result.jump_targets)} resolved, "
                 f"{len(result.unresolved_jumps)} unresolved"
                 + (", fully resolved)" if result.fully_resolved else ")"))
    lines.append(f"  valid targets (reachable JUMPDESTs): "
                 f"{len(result.valid_targets)}")
    lines.append(f"  merge points: {len(result.merge_points)}, "
                 f"dead code: {result.dead_bytes} bytes")

    lines.append("")
    lines.append("== blocks ==")
    lines.append(f"  {'id':>4} {'pc range':>15} {'term':<10} {'h':>4} "
                 f"{'merge':>7}  successors")
    for block in result.blocks:
        dead = block.block_id not in result.reachable
        height = "?" if block.entry_height is None else block.entry_height
        merge = result.block_merge_pc[block.block_id]
        lines.append(
            f"  {block.block_id:>4} "
            f"{block.start_pc:#7x}..{block.end_pc:#6x} "
            f"{(block.terminator or 'fall'):<10} {height:>4} "
            f"{(f'{merge:#x}' if merge >= 0 else '-'):>7}  "
            + ("DEAD" if dead else _succ_str(block, result)))

    lines.append("")
    lines.append("== jump sites ==")
    if not result.jump_targets and not result.unresolved_jumps:
        lines.append("  (none reachable)")
    for site in sorted(result.jump_targets):
        targets = result.jump_targets[site]
        dest = ", ".join(f"{t:#x}" for t in targets) if targets \
            else "(provably throws)"
        lines.append(f"  {site:#6x} -> {dest}")
    for site in sorted(result.unresolved_jumps):
        lines.append(f"  {site:#6x} -> ?  (unresolved: conservative "
                     f"fan-out to every JUMPDEST)")

    lines.append("")
    lines.append("== merge points (branch site -> postdom pc) ==")
    if result.branch_merge_pc:
        for site in sorted(result.branch_merge_pc):
            lines.append(f"  {site:#6x} -> {result.branch_merge_pc[site]:#x}")
    else:
        lines.append("  (no branch reconverges before exit)")

    regions = _dead_regions(result)
    lines.append("")
    lines.append("== statically dead code ==")
    if regions:
        for start, end in regions:
            lines.append(f"  {start:#6x}..{end:#x}  ({end - start} bytes)")
    else:
        lines.append("  (none)")
    return "\n".join(lines)


def _screened_module_names(disassembly) -> List[str]:
    """Detection modules the module screen would skip wholesale for this
    contract (hook opcodes unreachable)."""
    from mythril_tpu.analysis.module import ModuleLoader
    from mythril_tpu.analysis.module.base import EntryPoint
    from mythril_tpu.analysis.module_screen import screen_modules

    modules = ModuleLoader().get_detection_modules(EntryPoint.CALLBACK)
    _, skipped = screen_modules(modules, disassembly)
    return sorted(type(m).__name__ for m in skipped)


def _taints_str(taints) -> str:
    return ",".join(sorted(taints)) if taints else "-"


def taint_report(summary, disassembly) -> str:
    lines: List[str] = []
    lines.append("")
    lines.append("== taint: functions ==")
    if summary.functions:
        for fn in summary.functions:
            lines.append(f"  {fn.entry_pc:#6x} {fn.selector or '(fallback)':<12} "
                         f"{fn.name}  ({len(fn.blocks)} block(s))")
    else:
        lines.append("  (no dispatcher recovered — single partition)")

    lines.append("")
    lines.append("== taint: natural loops ==")
    if summary.loops:
        for loop in summary.loops:
            backs = ", ".join(f"{pc:#x}" for pc in loop.back_edge_pcs)
            lines.append(f"  header {loop.header_pc:#6x} depth {loop.depth} "
                         f"({len(loop.blocks)} block(s), back edges: {backs})")
    else:
        lines.append("  (none)")

    lines.append("")
    lines.append("== taint: sink sites (operand 0 = top of stack) ==")
    converged = "converged" if summary.converged else "NOT converged (saturated)"
    lines.append(f"  {len(summary.sink_sites)} site(s), "
                 f"{summary.rounds} storage round(s), {converged}")
    for pc in sorted(summary.sink_sites):
        site = summary.sink_sites[pc]
        operands = "  ".join(
            f"[{i}]={_taints_str(t)}"
            for i, t in enumerate(site.operand_taint))
        lines.append(f"  {pc:#6x} {site.op:<14} {operands}")

    lines.append("")
    lines.append("== taint: module screen ==")
    skipped = _screened_module_names(disassembly)
    if skipped:
        lines.append(f"  {len(skipped)} module(s) skipped wholesale "
                     "(hook opcodes unreachable):")
        for name in skipped:
            lines.append(f"    {name}")
    else:
        lines.append("  (no whole-module skips)")
    return "\n".join(lines)


def as_json(result) -> dict:
    """The dense tables, JSON-serializable (dict keys become strings)."""
    return {
        "code_length": result.code_length,
        "blocks": [
            {"id": b.block_id, "start_pc": b.start_pc, "end_pc": b.end_pc,
             "terminator": b.terminator, "entry_height": b.entry_height,
             "successors": sorted(b.successors),
             "reachable": b.block_id in result.reachable}
            for b in result.blocks],
        "exit_id": result.exit_id,
        "n_edges": result.n_edges,
        "pc_to_block": list(result.pc_to_block),
        "block_merge_pc": list(result.block_merge_pc),
        "branch_merge_pc": {str(pc): merge for pc, merge
                            in sorted(result.branch_merge_pc.items())},
        "valid_targets": sorted(result.valid_targets),
        "jump_targets": {str(pc): list(targets) for pc, targets
                         in sorted(result.jump_targets.items())},
        "unresolved_jumps": sorted(result.unresolved_jumps),
        "dead_mask": [int(dead) for dead in result.dead_mask],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.cfaview",
        description="static control-flow-analysis report for EVM "
                    "runtime bytecode")
    parser.add_argument("contract",
                        help="hex bytecode file, raw hex string, or a "
                             f"vendored name ({'/'.join(_VENDORED)})")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw cfa tables as JSON")
    parser.add_argument("--taint", action="store_true",
                        help="append the source->sink taint summary "
                             "(functions, loops, sink verdicts, module "
                             "screen)")
    args = parser.parse_args(argv)
    try:
        bytecode = load_bytecode(args.contract)
    except (OSError, ValueError) as error:
        print(f"cfaview: cannot load {args.contract!r}: {error}",
              file=sys.stderr)
        return 2

    from mythril_tpu.frontends.disassembler import Disassembly
    from mythril_tpu.staticanalysis import build_cfa

    disassembly = Disassembly(bytecode)
    result = build_cfa(disassembly)
    if result is None:
        print("cfaview: cfa pass bailed (empty code or over the "
              "MYTHRIL_TPU_CFA_MAX_BLOCKS budget)", file=sys.stderr)
        return 2
    summary = None
    if args.taint:
        from mythril_tpu.staticanalysis import get_summary

        summary = get_summary(disassembly)
        if summary is None:
            print("cfaview: taint summary unavailable (pass disabled "
                  "via MYTHRIL_TPU_TAINT=0, or the fixpoint bailed)",
                  file=sys.stderr)
            return 2
    if args.json:
        import json
        doc = as_json(result)
        if summary is not None:
            doc["taint"] = summary.to_json()
            doc["taint"]["screened_modules"] = \
                _screened_module_names(disassembly)
        print(json.dumps(doc, indent=2))
    else:
        text = report(result, disassembly.instruction_list)
        if summary is not None:
            text += "\n" + taint_report(summary, disassembly)
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
