"""Serve-daemon smoke for the pre-merge gate (tools/check.sh).

Full process-level lifecycle, CPU-only and CDCL-only so it stays cheap:

1. start `myth-tpu serve` (unix-socket mode, warmup on over an empty
   manifest) as a subprocess;
2. wait for the socket, then send ping + one analyze request for the
   mini killable contract + a metrics scrape + shutdown over one client
   connection;
3. require the analyze reply to find the SELFDESTRUCT issue (carrying a
   correlation id that also shows up in the structured log), the metrics
   reply to carry a Prometheus exposition that mentions the request
   counter, and the daemon to exit 0 after the drain.

Prints ``SERVE_SMOKE=ok`` on success; any failure exits non-zero with a
diagnostic. The caller bounds the wall clock (check.sh wraps this in
`timeout`)."""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mini_contract() -> str:
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)

    runtime = assemble(dispatcher({
        "activatekillability()": "PUSH1 0x01\nPUSH1 0x00\nSSTORE\nSTOP",
        "commencekilling()": ("PUSH1 0x00\nSLOAD\nPUSH1 0x01\nEQ\n"
                              "PUSH @do_kill\nJUMPI\nSTOP\n"
                              "do_kill:\nJUMPDEST\nCALLER\nSELFDESTRUCT"),
    }))
    return creation_wrapper(runtime).hex()


def main() -> int:
    from mythril_tpu.serve import client

    workdir = tempfile.mkdtemp(prefix="serve_smoke_")
    socket_path = os.path.join(workdir, "serve.sock")
    manifest_path = os.path.join(workdir, "warmset.json")
    slog_path = os.path.join(workdir, "serve.slog")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MYTHRIL_TPU_SLOG=slog_path)
    daemon = subprocess.Popen(
        [sys.executable, "-m", "mythril_tpu.interfaces.cli", "serve",
         "--socket", socket_path, "--manifest", manifest_path],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        deadline = time.monotonic() + 90
        while not os.path.exists(socket_path):
            if daemon.poll() is not None:
                print("serve_smoke: daemon died before binding:\n"
                      + daemon.stderr.read().decode(errors="replace"),
                      file=sys.stderr)
                return 1
            if time.monotonic() > deadline:
                print("serve_smoke: socket never appeared", file=sys.stderr)
                return 1
            time.sleep(0.2)

        replies = client.roundtrip(
            [{"op": "ping", "id": "smoke-ping"},
             {"op": "analyze", "id": "smoke-analyze",
              "code": _mini_contract(), "transaction_count": 2,
              "deadline_ms": 120_000},
             {"op": "metrics", "id": "smoke-metrics"},
             {"op": "shutdown", "id": "smoke-shutdown"}],
            socket_path=socket_path, timeout=120)

        problems = []
        if not all(reply.get("ok") for reply in replies):
            problems.append(f"non-ok reply: {replies}")
        analyze = replies[1]
        if analyze.get("issue_count", 0) < 1:
            problems.append(f"expected >=1 issue, got {analyze}")
        if "warm" not in analyze:
            problems.append(f"no warm/cold accounting in {analyze}")
        cid = analyze.get("correlation_id", "")
        if not cid:
            problems.append(f"analyze reply carries no correlation_id: "
                            f"{analyze}")
        scrape = replies[2]
        exposition = scrape.get("exposition", "")
        if "mythril_tpu_serve_requests_total" not in exposition:
            problems.append("metrics exposition lacks the request counter:"
                            f" {exposition[:400]!r}")
        if not str(scrape.get("content_type", "")).startswith("text/plain"):
            problems.append(f"bad metrics content_type in {scrape}")
        try:
            with open(slog_path, encoding="utf-8") as handle:
                slog_text = handle.read()
        except OSError:
            slog_text = ""
        if cid and cid not in slog_text:
            problems.append(f"correlation id {cid!r} absent from slog "
                            f"{slog_path}")
        daemon.wait(timeout=30)
        if daemon.returncode != 0:
            problems.append(f"daemon exited {daemon.returncode}:\n"
                            + daemon.stderr.read().decode(errors="replace"))
        if not os.path.exists(manifest_path) and analyze.get("warm", {}) \
                .get("cold_buckets"):
            problems.append("compiled buckets but wrote no manifest")
        if problems:
            print("serve_smoke: FAIL\n" + "\n".join(problems),
                  file=sys.stderr)
            return 1
        print(f"SERVE_SMOKE=ok issues={analyze['issue_count']} "
              f"elapsed_ms={analyze.get('elapsed_ms')} cid={cid}")
        return 0
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
