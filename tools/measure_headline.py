#!/usr/bin/env python
"""BASELINE.md headline-config measurement (VERDICT r4 #7).

The reference README's flagship invocations are
  myth analyze solidity_examples/killbilly.sol -t 3
  myth analyze solidity_examples/BECToken.sol -t 4 -m IntegerArithmetics
(/root/reference/solidity_examples/). This environment has no solc, so the
contracts are VENDORED here as hand-assembled semantic equivalents built
with the in-repo assembler (frontends/asm.py) — same storage layout, same
require structure, same keccak-keyed mappings, same vulnerable paths:

- killbilly: is_killable @ slot0, approved_killers @ mapping slot1;
  killerize(address) -> activatekillability() -> commencekilling()
  selfdestructs: the SWC-106 3-transaction chain.
- BECToken batchTransfer: cnt * _value overflows (CVE-2018-10299) before
  the balance check, so a huge _value passes require(balances >= amount):
  the SWC-101 the reference headline finds with -m IntegerArithmetics.

Usage: python tools/measure_headline.py [--engine host|tpu] [--budget 300]
Writes headline_{engine}.json at the repo root.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: mapping access: key = keccak256(pad32(k) ++ pad32(slot))
def _mapping_load(key_src: str, slot: int) -> str:
    return (f"{key_src}\nPUSH1 0x00\nMSTORE\n"
            f"PUSH1 {hex(slot)}\nPUSH1 0x20\nMSTORE\n"
            "PUSH1 0x40\nPUSH1 0x00\nSHA3")


KILLBILLY = {
    # killerize(address addr): approved_killers[addr] = true
    "killerize(address)":
        "PUSH1 0x01\n"                       # value true
        + _mapping_load("PUSH1 0x04\nCALLDATALOAD", 1) + "\n"
        "SSTORE\nSTOP",
    # activatekillability(): require(approved_killers[msg.sender]);
    # is_killable = true
    "activatekillability()":
        _mapping_load("CALLER", 1) + "\n"
        "SLOAD\nPUSH @ok\nJUMPI\n"
        "PUSH1 0x00\nPUSH1 0x00\nREVERT\n"
        "ok:\nJUMPDEST\nPUSH1 0x01\nPUSH1 0x00\nSSTORE\nSTOP",
    # commencekilling(): require(is_killable); selfdestruct(msg.sender)
    "commencekilling()":
        "PUSH1 0x00\nSLOAD\nPUSH @kill\nJUMPI\n"
        "PUSH1 0x00\nPUSH1 0x00\nREVERT\n"
        "kill:\nJUMPDEST\nCALLER\nSELFDESTRUCT",
}

#: balances mapping at slot 0 (the fields the CVE path touches)
BECTOKEN = {
    # transfer(address to, uint256 value): balances[caller] -= v (checked),
    # balances[to] += v — the benign baseline function
    "transfer(address,uint256)":
        "PUSH1 0x24\nCALLDATALOAD\n"                  # v
        + _mapping_load("CALLER", 0) + "\n"           # key(caller)
        "DUP1\nSLOAD\n"                               # v key bal
        "DUP3\nDUP2\nLT\nPUSH @bail\nJUMPI\n"         # bal < v -> bail
        "SUB\nSWAP1\nSSTORE\n"                        # balances[caller]=bal-v
        "PUSH1 0x24\nCALLDATALOAD\n"
        + _mapping_load("PUSH1 0x04\nCALLDATALOAD", 0) + "\n"
        "DUP1\nSLOAD\n"                               # v key bal2
        "DUP3\nADD\nSWAP1\nSSTORE\nSTOP\n"            # balances[to]=bal2+v
        "bail:\nJUMPDEST\nPUSH1 0x00\nPUSH1 0x00\nREVERT",
    # batchTransfer(address[] receivers, uint256 value):
    #   cnt = receivers.length; amount = cnt * value   <-- SWC-101 overflow
    #   require(0 < cnt <= 20); require(value > 0 && balances[caller] >= amount)
    #   balances[caller] -= amount; balances[receivers[0]] += value (loop body
    #   representative: the overflow is upstream of the loop)
    "batchTransfer(address[],uint256)":
        "PUSH1 0x04\nCALLDATALOAD\nPUSH1 0x04\nADD\nCALLDATALOAD\n"  # cnt
        "DUP1\nISZERO\nPUSH @bail\nJUMPI\n"           # cnt == 0 -> bail
        "DUP1\nPUSH1 0x14\nLT\nPUSH @bail\nJUMPI\n"   # 20 < cnt -> bail
        "PUSH1 0x24\nCALLDATALOAD\n"                  # cnt value
        "DUP1\nISZERO\nPUSH @bail\nJUMPI\n"           # value == 0 -> bail
        "MUL\n"                                       # amount = cnt*value
        + _mapping_load("CALLER", 0) + "\n"           # amount key
        "DUP1\nSLOAD\n"                               # amount key bal
        "DUP3\nDUP2\nLT\nPUSH @bail\nJUMPI\n"         # bal < amount -> bail
        "SUB\nSWAP1\nSSTORE\n"                        # balances[caller] -=
        "PUSH1 0x24\nCALLDATALOAD\n"                  # value
        + _mapping_load("PUSH1 0x24\nPUSH1 0x04\nCALLDATALOAD\nADD\n"
                        "CALLDATALOAD", 0) + "\n"     # key(receivers[0])
        "DUP1\nSLOAD\nDUP3\nADD\nSWAP1\nSSTORE\n"     # balances[r0] += value
        "PUSH1 0x01\nPUSH1 0x00\nMSTORE\nPUSH1 0x20\nPUSH1 0x00\nRETURN\n"
        "bail:\nJUMPDEST\nPUSH1 0x00\nPUSH1 0x00\nREVERT",
}


def run(name, runtime_src, tx_count, modules, engine, budget):
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontends.asm import (assemble, creation_wrapper,
                                           dispatcher)
    from mythril_tpu.smt.solver.solver import reset_solver_backend

    reset_callback_modules()
    reset_solver_backend()
    creation = creation_wrapper(assemble(dispatcher(runtime_src)))
    start = time.perf_counter()
    wrapper = SymExecWrapper(
        creation.hex(), address=None, strategy="bfs", max_depth=128,
        execution_timeout=budget, create_timeout=60,
        transaction_count=tx_count, compulsory_statespace=False,
        modules=modules, engine=engine)
    issues = fire_lasers(wrapper, white_list=modules)
    elapsed = time.perf_counter() - start
    laser = wrapper.laser
    states = laser.executed_nodes + getattr(laser, "frontier_lane_steps", 0)
    result = {
        "states": states,
        "elapsed_s": round(elapsed, 2),
        "states_per_sec": round(states / max(elapsed, 1e-9), 1),
        "swc": sorted({i.swc_id for i in issues}),
        "n_issues": len(issues),
        "forks_on_device": getattr(laser, "frontier_forks", 0),
    }
    print(json.dumps({"contract": name, "engine": engine, **result}),
          flush=True)
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--engine", default="host", choices=["host", "tpu"])
    parser.add_argument("--budget", type=int, default=300)
    args = parser.parse_args()
    results = {
        # reference README flagship: myth analyze killbilly.sol -t 3
        "killbilly_t3": run("killbilly_t3", KILLBILLY, 3,
                            ["AccidentallyKillable"], args.engine,
                            args.budget),
        # myth analyze BECToken.sol -t 4 -m IntegerArithmetics (the -t 4 of
        # the reference bounds the search; the overflow fires in tx 1)
        "bectoken_t4_integer": run("bectoken_t4_integer", BECTOKEN, 4,
                                   ["IntegerArithmetics"], args.engine,
                                   args.budget),
    }
    out = os.path.join(REPO, f"headline_{args.engine}.json")
    with open(out, "w") as handle:
        json.dump({"engine": args.engine, "budget_s": args.budget,
                   "results": results}, handle, indent=1)
    print(json.dumps({"written": out}))


if __name__ == "__main__":
    main()
