"""Frontier-telemetry CLI for mythril-tpu traces and metrics snapshots.

    python -m tools.frontierview TRACE.json [--metrics METRICS.json]

Reads the Perfetto counter ('C') tracks that the device-resident
frontier telemetry plane emits per chunk (``parallel/frontier.py``
decodes the packed counter words riding the existing summary download
and samples them via ``observe/trace.py``'s counter API) and prints:

* the **lane-occupancy timeline** — one row per chunk with running /
  DFS-stack / escaped lane counts (``frontier.lanes``) and arena fill
  (``frontier.arena``) as stacked text bars;
* the **opcode-class heatmap** — total per-class executed-instruction
  counts across the run (``frontier.ops``), ranked;
* the **escape/prune cause table** — why lanes left the device
  (``frontier.causes``) and the lifecycle totals — reseeds, deaths,
  fork waits, cold-SLOAD pauses (``frontier.lifecycle``);
* **per-loop / per-merge-tag occupancy** (``frontier.tags``): how many
  lane-steps ran at each ``loop@pc`` / ``merge@pc`` site the static
  analysis annotated;
* **state-merge events** (``frontier.merges``): reconverged
  fork-sibling pairs the veritesting pass collapsed, and the ITE
  blends it allocated doing so;
* **fleet occupancy** (``frontier.fleet``): lane-steps per contract in
  a ``--fleet`` run with a Jain fairness index — how evenly the packed
  frontier split the device between corpus members.

With ``--metrics`` it also summarizes an fsync-atomic metrics snapshot
(``analyze --metrics-out`` / ``MYTHRIL_TPU_METRICS`` /
``observe.metrics.write_snapshot``): the ``frontier.telemetry.*``
counters, gauges, and labeled histograms, plus the
``frontier.merge.*`` slice — merges per join-point tag, the
``blocked_by.*`` gate breakdown (which equality gate refused
reconverged-looking pairs; memory rows are what absint join windows
unblock), lanes
retired, and the ITE-depth (blended-slots-per-pair) histogram.

Stdlib-only (json/argparse): usable on a workstation without jax.
Exit codes: 0 on success (even when the trace has no counter tracks —
the report says so), 2 when a file is missing or malformed.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

#: bar width for the occupancy timeline and heatmap bars
_BAR = 40

#: the counter tracks the frontier decode emits (observe/trace.py)
LANES_TRACK = "frontier.lanes"
ARENA_TRACK = "frontier.arena"
OPS_TRACK = "frontier.ops"
CAUSES_TRACK = "frontier.causes"
LIFECYCLE_TRACK = "frontier.lifecycle"
TAGS_TRACK = "frontier.tags"
MERGES_TRACK = "frontier.merges"
FLEET_TRACK = "frontier.fleet"
SHARD_TRACK = "frontier.shard"


def load_trace(path: str) -> Tuple[List[dict], Dict[str, object]]:
    """Parse a trace_event document (object or bare-array format) —
    same acceptance as tools/traceview.py. Raises ValueError."""
    with open(path, "r", encoding="utf-8") as handle:
        doc = json.load(handle)
    if isinstance(doc, list):
        events, other = doc, {}
    elif isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list):
        events, other = doc["traceEvents"], dict(doc.get("otherData") or {})
    else:
        raise ValueError(
            "not a trace_event document: expected a JSON array of events "
            "or an object with a 'traceEvents' list")
    for event in events:
        if not isinstance(event, dict) or "ph" not in event:
            raise ValueError("malformed trace event (no 'ph' field): "
                             f"{event!r:.120}")
    return events, other


def counter_samples(events: List[dict], track: str) -> List[dict]:
    """Time-ordered 'C' samples for one counter track: each a dict of
    {ts (us), values {series: number}}."""
    samples = []
    for event in events:
        if event.get("ph") != "C" or event.get("name") != track:
            continue
        values = {}
        for key, value in (event.get("args") or {}).items():
            if isinstance(value, (int, float)):
                values[key] = value
        samples.append({"ts": float(event.get("ts", 0.0)), "values": values})
    samples.sort(key=lambda s: s["ts"])
    return samples


def sum_series(samples: List[dict]) -> Dict[str, float]:
    """Per-series totals across samples (the tracks carry per-chunk
    deltas, so the sum is the run total)."""
    totals: Dict[str, float] = {}
    for sample in samples:
        for key, value in sample["values"].items():
            totals[key] = totals.get(key, 0.0) + value
    return totals


def _fmt_ts(us: float) -> str:
    if us < 1_000_000:
        return f"{us / 1_000:.1f}ms"
    return f"{us / 1_000_000:.2f}s"


def _bar(value: float, peak: float, width: int = _BAR) -> str:
    if peak <= 0:
        return ""
    return "#" * max(1 if value > 0 else 0,
                     int(round(value / peak * width)))


def _ranked_table(totals: Dict[str, float], title: str,
                  unit: str) -> List[str]:
    lines = ["", f"== {title} =="]
    total = sum(totals.values())
    if total <= 0:
        lines.append("  (no samples)")
        return lines
    peak = max(totals.values())
    for name, value in sorted(totals.items(), key=lambda kv: -kv[1]):
        if value <= 0:
            continue
        share = value / total * 100
        lines.append(f"  [{share:5.1f}%] {name:<16} {value:>12.0f} {unit}  "
                     f"|{_bar(value, peak):<{_BAR}}|")
    return lines


def _timeline_section(lanes: List[dict], arena: List[dict]) -> List[str]:
    lines = ["", "== lane-occupancy timeline (per chunk) =="]
    if not lanes:
        lines.append("  (no frontier.lanes samples — telemetry off or "
                     "host engine)")
        return lines
    arena_at = {s["ts"]: s["values"].get("nodes", 0) for s in arena}
    arena_ts = sorted(arena_at)
    peak = max(max(s["values"].get("running", 0),
                   s["values"].get("stack", 0),
                   s["values"].get("escaped", 0)) for s in lanes) or 1
    lines.append(f"  {len(lanes)} chunk(s); bar scale: {peak:.0f} lanes "
                 "(r=running, s=DFS stack, e=escaped)")
    for sample in lanes:
        values = sample["values"]
        running = values.get("running", 0)
        stack = values.get("stack", 0)
        escaped = values.get("escaped", 0)
        # nearest arena sample at-or-before this chunk's timestamp
        nodes = 0
        for ts in arena_ts:
            if ts <= sample["ts"]:
                nodes = arena_at[ts]
            else:
                break
        lines.append(
            f"  @{_fmt_ts(sample['ts']):>9}  "
            f"r{running:>5.0f} |{_bar(running, peak, 14):<14}| "
            f"s{stack:>5.0f} |{_bar(stack, peak, 14):<14}| "
            f"e{escaped:>5.0f} |{_bar(escaped, peak, 14):<14}| "
            f"arena {nodes:.0f}")
    return lines


def _lifecycle_section(totals: Dict[str, float]) -> List[str]:
    lines = ["", "== lane lifecycle (run totals) =="]
    if not totals:
        lines.append("  (no frontier.lifecycle samples)")
        return lines
    for name in sorted(totals):
        lines.append(f"  {name:<16} {totals[name]:>12.0f}")
    return lines


def _tags_section(totals: Dict[str, float]) -> List[str]:
    lines = ["", "== per-loop / per-merge-tag occupancy (lane-steps) =="]
    if not totals:
        lines.append("  (no frontier.tags samples — contract had no "
                     "annotated loop headers or merge points)")
        return lines
    peak = max(totals.values()) or 1
    for name, value in sorted(totals.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {name:<16} {value:>12.0f}  "
                     f"|{_bar(value, peak):<{_BAR}}|")
    return lines


def _fleet_section(totals: Dict[str, float]) -> List[str]:
    """Per-contract occupancy of a fleet run (lane-steps per member) and
    a fairness number: 1.0 = every contract got an equal share of the
    device, lower = one member starved the others."""
    lines = ["", "== fleet occupancy (lane-steps per contract) =="]
    if not totals:
        lines.append("  (no frontier.fleet samples — single-contract run "
                     "or --fleet off)")
        return lines
    shares = [v for v in totals.values() if v > 0]
    total = sum(shares)
    peak = max(totals.values()) or 1
    for name, value in sorted(totals.items(), key=lambda kv: -kv[1]):
        share = value / total * 100 if total else 0.0
        lines.append(f"  [{share:5.1f}%] {name:<16} {value:>12.0f}  "
                     f"|{_bar(value, peak):<{_BAR}}|")
    if shares:
        # Jain's fairness index: (sum x)^2 / (n * sum x^2)
        fairness = total * total / (len(shares)
                                    * sum(v * v for v in shares))
        lines.append(f"  fairness (Jain): {fairness:.2f} over "
                     f"{len(shares)} active contract(s)")
    return lines


def _shard_section(totals: Dict[str, float]) -> List[str]:
    """Sharded fleet: per-device (logical shard block) load share bars
    — running lanes + pending rows, summed over chunks — plus the Jain
    fairness of those shares (the device-resident steal pass exists to
    push this toward 1.0)."""
    lines = ["", "== sharded fleet (per-device load share) =="]
    if not totals:
        lines.append("  (no frontier.shard samples — unsharded run; set "
                     "MYTHRIL_TPU_FLEET_SHARD or run on a multi-device "
                     "mesh)")
        return lines
    shares = [v for v in totals.values() if v > 0]
    total = sum(shares)
    peak = max(totals.values()) or 1
    for name, value in sorted(totals.items()):
        share = value / total * 100 if total else 0.0
        lines.append(f"  [{share:5.1f}%] {name:<16} {value:>12.0f}  "
                     f"|{_bar(value, peak):<{_BAR}}|")
    if shares:
        fairness = total * total / (len(shares)
                                    * sum(v * v for v in shares))
        lines.append(f"  fairness (Jain): {fairness:.2f} over "
                     f"{len(shares)} device(s)")
    return lines


def _merges_section(totals: Dict[str, float]) -> List[str]:
    lines = ["", "== state-merge events (veritesting) =="]
    if not totals:
        lines.append("  (no frontier.merges samples — state merging off "
                     "(--no-state-merge / MYTHRIL_TPU_STATE_MERGE=0) or "
                     "no lanes reconverged)")
        return lines
    merged = totals.get("merged", 0)
    ites = totals.get("ites", 0)
    lines.append(f"  {'pairs merged':<16} {merged:>12.0f}  "
                 "(one lane retired each)")
    lines.append(f"  {'ITE blends':<16} {ites:>12.0f}  "
                 f"({ites / merged:.1f} per pair)" if merged else
                 f"  {'ITE blends':<16} {ites:>12.0f}")
    return lines


def report(events: List[dict], other: Dict[str, object]) -> str:
    lines: List[str] = ["== frontier telemetry =="]
    for key in ("engine", "contracts", "started_at"):
        if key in other:
            lines.append(f"  {key}: {other[key]}")
    lanes = counter_samples(events, LANES_TRACK)
    arena = counter_samples(events, ARENA_TRACK)
    ops = sum_series(counter_samples(events, OPS_TRACK))
    causes = sum_series(counter_samples(events, CAUSES_TRACK))
    lifecycle = sum_series(counter_samples(events, LIFECYCLE_TRACK))
    tags = sum_series(counter_samples(events, TAGS_TRACK))
    merges = sum_series(counter_samples(events, MERGES_TRACK))
    fleet = sum_series(counter_samples(events, FLEET_TRACK))
    shard = sum_series(counter_samples(events, SHARD_TRACK))
    n_counter = sum(1 for e in events if e.get("ph") == "C")
    lines.append(f"  counter samples: {n_counter} "
                 f"({len(lanes)} chunk(s) with lane telemetry)")
    if not n_counter:
        lines.append("  hint: run with --trace-out and the frontier "
                     "telemetry knob on (MYTHRIL_TPU_FRONTIER_TELEMETRY, "
                     "default 1) and --engine tpu")
    lines.extend(_timeline_section(lanes, arena))
    lines.extend(_ranked_table(ops, "opcode-class heatmap (executed)",
                               "ops"))
    lines.extend(_ranked_table(causes, "escape/prune causes", "lanes"))
    lines.extend(_lifecycle_section(lifecycle))
    lines.extend(_tags_section(tags))
    lines.extend(_merges_section(merges))
    lines.extend(_fleet_section(fleet))
    lines.extend(_shard_section(shard))
    return "\n".join(lines)


def _metrics_slice(snapshot: Dict[str, object], prefix: str,
                   empty_note: str) -> List[str]:
    """Render every `prefix`-named entry of a metrics snapshot."""
    lines = [f"== metrics snapshot ({prefix}*) =="]
    rows = {name: value for name, value in snapshot.items()
            if str(name).startswith(prefix)}
    if not rows:
        lines.append(f"  ({empty_note})")
        return lines
    for name in sorted(rows):
        value = rows[name]
        short = name[len(prefix):]
        if isinstance(value, dict) and value and all(
                isinstance(v, dict) for v in value.values()):
            # labeled histogram: {label: {count, sum, ...}}
            lines.append(f"  {short}:")
            for label, stats in sorted(
                    value.items(),
                    key=lambda kv: -float(kv[1].get("sum", 0) or 0)):
                line = (f"    {label:<16} sum {stats.get('sum', 0):>12} "
                        f" x{stats.get('count', 0)}")
                if "p95" in stats:
                    line += f"  p95 {stats['p95']}"
                lines.append(line)
        elif isinstance(value, dict):
            detail = ", ".join(f"{k}={v}" for k, v in sorted(value.items()))
            lines.append(f"  {short:<24} {detail}")
        else:
            lines.append(f"  {short:<24} {value}")
    return lines


def _blocked_by_section(snapshot: Dict[str, object]) -> List[str]:
    """Rank the frontier.merge.blocked_by.* gate counters: which
    equality gate refused reconverged-looking pairs. A memory-dominated
    profile is the absint signal — proven join windows
    (MYTHRIL_TPU_ABSINT) unblock exactly that gate; mem_sym / tstore /
    depth rows need deeper representation work, not wider windows."""
    prefix = "frontier.merge.blocked_by."
    rows = {str(name)[len(prefix):]: value
            for name, value in snapshot.items()
            if str(name).startswith(prefix)
            and isinstance(value, (int, float))}
    lines = ["== merge blocked-by gates =="]
    if not rows:
        lines.append("  (no blocked pairs recorded — every "
                     "reconverged-looking pair merged, or no merge "
                     "passes ran)")
        return lines
    total = sum(rows.values()) or 1
    for gate, count in sorted(rows.items(), key=lambda kv: -kv[1]):
        share = count / total
        bar = "#" * max(1, int(round(share * 24)))
        lines.append(f"  {gate:<14} {count:>10.0f}  {share:>5.1%}  {bar}")
    return lines


def metrics_report(snapshot: Dict[str, object]) -> str:
    """Summarize the frontier.telemetry.* and frontier.merge.* slices of
    a metrics snapshot (observe.metrics.write_snapshot /
    --metrics-out), including the blocked-by gate breakdown."""
    lines = [""]
    lines.extend(_metrics_slice(
        snapshot, "frontier.telemetry.",
        "snapshot has no frontier.telemetry entries"))
    lines.append("")
    lines.extend(_metrics_slice(
        snapshot, "frontier.merge.",
        "no merge passes ran — state merging off or no reconverged "
        "lanes"))
    lines.append("")
    lines.extend(_blocked_by_section(snapshot))
    lines.append("")
    lines.extend(_metrics_slice(
        snapshot, "serve.worker.",
        "no worker pool — serve ran without --workers"))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.frontierview",
        description="frontier-telemetry report (occupancy timeline, "
                    "opcode heatmap, escape causes, tag occupancy) for a "
                    "mythril-tpu trace")
    parser.add_argument("trace", nargs="?", default=None,
                        help="trace_event JSON written via "
                             "MYTHRIL_TPU_TRACE / --trace-out")
    parser.add_argument("--metrics", default=None, metavar="PATH",
                        help="metrics snapshot JSON written via "
                             "--metrics-out / MYTHRIL_TPU_METRICS")
    args = parser.parse_args(argv)
    if not args.trace and not args.metrics:
        parser.error("need a trace file, --metrics PATH, or both")
    out: List[str] = []
    if args.trace:
        try:
            events, other = load_trace(args.trace)
        except (OSError, ValueError, json.JSONDecodeError) as error:
            print(f"frontierview: cannot read {args.trace}: {error}",
                  file=sys.stderr)
            return 2
        out.append(report(events, other))
    if args.metrics:
        try:
            with open(args.metrics, "r", encoding="utf-8") as handle:
                snapshot = json.load(handle)
            if not isinstance(snapshot, dict):
                raise ValueError("metrics snapshot must be a JSON object")
        except (OSError, ValueError, json.JSONDecodeError) as error:
            print(f"frontierview: cannot read {args.metrics}: {error}",
                  file=sys.stderr)
            return 2
        out.append(metrics_report(snapshot))
    print("\n".join(out).lstrip("\n"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
