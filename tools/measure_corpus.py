#!/usr/bin/env python
"""BASELINE.md corpus measurement (VERDICT r3 next-round #9).

Runs the reference's deployed-bytecode corpus
(tests/testdata/inputs/*.sol.o, read from /root/reference) through
`analyze --bin-runtime` under both engines, recording per-contract:
states explored, wall time, states/sec, and the SWC issue set. Emits
corpus_{engine}.json at the repo root; bench.py attaches the summaries to
the driver metric line as `corpus` extras.

The reference itself (CPU/z3) is not runnable in this environment (no
z3-solver); per BASELINE.md the host engine — the same worklist design the
reference implements — is the measured stand-in baseline.

Usage: python tools/measure_corpus.py [--engine host|tpu] [--budget 90]
       [--contracts a,b,c]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
INPUTS = "/root/reference/tests/testdata/inputs"

#: every deployed-bytecode input the reference ships (19 files) — the
#: measured corpus covers the whole set (VERDICT r4 #8)
DEFAULT_CONTRACTS = [
    "calls.sol.o", "coverage.sol.o", "environments.sol.o",
    "ether_send.sol.o", "exceptions.sol.o", "exceptions_0.8.0.sol.o",
    "extcall.sol.o", "flag_array.sol.o", "kinds_of_calls.sol.o",
    "metacoin.sol.o", "multi_contracts.sol.o", "nonascii.sol.o",
    "origin.sol.o", "overflow.sol.o", "returnvalue.sol.o",
    "safe_funcs.sol.o", "suicide.sol.o", "symbolic_exec_bytecode.sol.o",
    "underflow.sol.o",
]


def measure(engine: str, budget: int, contracts, solver: str = "cdcl",
            batch_solve: bool = True):
    from mythril_tpu.analysis.security import (fire_lasers,
                                               reset_callback_modules)
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.smt.solver.solver import reset_solver_backend
    from mythril_tpu.smt.solver.solver_statistics import SolverStatistics
    from mythril_tpu.support.support_args import args as engine_args

    engine_args.solver = solver
    engine_args.batch_solve = batch_solve

    if engine == "tpu":
        # compile warm-up on a trivial contract so the first measured
        # contract's budget is exploration, not XLA compile (shapes are
        # bucketed — parallel/batch.py — so the compile carries over)
        import types

        reset_callback_modules()
        os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"] = "1"
        try:
            SymExecWrapper(
                types.SimpleNamespace(code="0x6001600101600055", name="warm"),
                address=0xD00D, strategy="bfs", max_depth=32,
                execution_timeout=150, create_timeout=30,
                transaction_count=1, compulsory_statespace=False,
                run_analysis_modules=False, engine="tpu")
        finally:
            del os.environ["MYTHRIL_TPU_SKIP_HOST_DRAIN"]

    results = {}
    for name in contracts:
        reset_callback_modules()
        reset_solver_backend()
        SolverStatistics().reset()
        start = time.perf_counter()
        import types

        try:
            with open(os.path.join(INPUTS, name)) as handle:
                code = handle.read().strip()
            contract = types.SimpleNamespace(code=code, name=name)
            wrapper = SymExecWrapper(
                contract, address=0xDEADBEEF, strategy="bfs", max_depth=128,
                execution_timeout=budget, create_timeout=30,
                transaction_count=2, compulsory_statespace=False,
                engine=engine)
            issues = fire_lasers(wrapper)
        except Exception as error:  # noqa: BLE001 — record and continue
            results[name] = {"error": f"{type(error).__name__}: {error}"}
            continue
        elapsed = time.perf_counter() - start
        laser = wrapper.laser
        states = laser.executed_nodes + getattr(laser,
                                                "frontier_lane_steps", 0)
        results[name] = {
            "states": states,
            "elapsed_s": round(elapsed, 2),
            "states_per_sec": round(states / max(elapsed, 1e-9), 1),
            "swc": sorted({i.swc_id for i in issues}),
            "sites": sorted({f"{i.swc_id}@{i.address}" for i in issues}),
            "n_issues": len(issues),
            "forks_on_device": getattr(laser, "frontier_forks", 0),
        }
        if solver == "jax":
            # batch-dispatch amortization per contract (occupancy, cache
            # hit rate, buckets compiled) — bench.py forwards the rollup
            results[name]["solver_batch"] = \
                SolverStatistics().batch_metrics()
        print(json.dumps({"contract": name, "engine": engine,
                          **results[name]}), flush=True)
    return results


def measure_parallel(engine: str, budget: int, contracts, n_workers: int,
                     solver: str = "cdcl", batch_solve: bool = True):
    """Contract-granularity fan-out: one subprocess per shard (round-robin),
    merged results. Per-contract process isolation means one contract's
    crash/hang cannot poison the sweep — the distributed tier's contract
    sharding, exercised locally."""
    import subprocess
    import tempfile

    shards = [contracts[rank::n_workers] for rank in range(n_workers)]
    procs = []
    for rank, shard in enumerate(shards):
        if not shard:
            continue
        out = tempfile.NamedTemporaryFile(
            suffix=f".shard{rank}.json", delete=False)
        out.close()
        cmd = [sys.executable, os.path.abspath(__file__),
               "--engine", engine, "--budget", str(budget),
               "--contracts", ",".join(shard), "--out", out.name,
               "--solver", solver]
        if not batch_solve:
            cmd.append("--no-batch-solve")
        procs.append((out.name, subprocess.Popen(cmd)))
    results = {}
    for out_name, proc in procs:
        proc.wait()
        try:
            with open(out_name) as handle:
                results.update(json.load(handle).get("contracts", {}))
        except Exception as error:  # noqa: BLE001
            results[f"shard:{out_name}"] = {
                "error": f"{type(error).__name__}: {error}"}
        finally:
            os.unlink(out_name)
    return results


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--engine", default="host", choices=["host", "tpu"])
    parser.add_argument("--budget", type=int, default=90)
    parser.add_argument("--contracts", default=None)
    parser.add_argument("--out", default=None)
    parser.add_argument("--solver", default="cdcl", choices=["cdcl", "jax"],
                        help="SAT backend for the sweep (--solver jax "
                        "exercises the batched device dispatch and records "
                        "solver_batch metrics per contract)")
    parser.add_argument("--no-batch-solve", action="store_true",
                        help="disable the batched device SAT dispatch "
                        "(A/B: one launch per query)")
    parser.add_argument(
        "--parallel", type=int, default=0, metavar="N",
        help="fan the sweep over N worker PROCESSES, each analyzing a "
        "contract shard in full isolation — the contract axis is the "
        "embarrassingly-parallel / DCN tier of SURVEY 2.3 (across hosts, "
        "shard by rank the same way). The single local TPU chip is "
        "single-tenant, so --parallel with --engine tpu serializes device "
        "access badly: use it for host-engine sweeps or multi-host runs.")
    args = parser.parse_args()
    contracts = (args.contracts.split(",") if args.contracts
                 else DEFAULT_CONTRACTS)
    batch_solve = not args.no_batch_solve
    if args.parallel > 1:
        results = measure_parallel(args.engine, args.budget, contracts,
                                   args.parallel, solver=args.solver,
                                   batch_solve=batch_solve)
    else:
        results = measure(args.engine, args.budget, contracts,
                          solver=args.solver, batch_solve=batch_solve)
    rates = [r["states_per_sec"] for r in results.values()
             if "states_per_sec" in r]
    summary = {
        "engine": args.engine,
        "budget_s": args.budget,
        "contracts": results,
        "median_states_per_sec": sorted(rates)[len(rates) // 2]
        if rates else None,
        "total_swc_findings": sum(r.get("n_issues", 0)
                                  for r in results.values()),
    }
    if args.solver == "jax":
        summary["solver"] = args.solver
        summary["batch_solve"] = batch_solve
        # whole-sweep rollup of the per-contract dispatch counters so the
        # corpus JSON (and bench.py's corpus extras) carries one
        # cache-hit/occupancy summary
        per = [r["solver_batch"] for r in results.values()
               if "solver_batch" in r]
        submitted = sum(p["submitted"] for p in per)
        flushes = sum(p["flushes"] for p in per)
        flushed = sum(p["flushed_queries"] for p in per)
        summary["solver_batch"] = {
            "submitted": submitted,
            "cache_hits": sum(p["cache_hits"] for p in per),
            "dedup_hits": sum(p["dedup_hits"] for p in per),
            "flushes": flushes,
            "flushed_queries": flushed,
            "occupancy": round(flushed / flushes, 2) if flushes else 0.0,
            "cache_hit_rate": round(
                sum(p["cache_hits"] for p in per) / submitted, 3)
            if submitted else 0.0,
            "buckets_compiled": max((p["buckets_compiled"] for p in per),
                                    default=0),
        }
    out_path = args.out or os.path.join(
        REPO, f"corpus_{args.engine}.json")
    with open(out_path, "w") as handle:
        json.dump(summary, handle, indent=1)
    print(json.dumps({"summary": {k: v for k, v in summary.items()
                                  if k != "contracts"}}))


if __name__ == "__main__":
    main()
